"""Two-level weighted-fair grant admission (delegate side).

The grant keeper used to hand grants to whichever waiter thread won the
``queue.Queue`` race — FIFO across *threads*, which under a `make -j500`
or an oversized-TU adversary is FIFO across *one client's* five hundred
threads: everyone else on the box starves.  This module replaces the
hand-out with stride scheduling over requestor keys: every client
carries a virtual pass; each grant goes to the waiting client with the
lowest pass, whose pass then advances by ``quantum / weight``.  Two
clients with equal weights therefore alternate no matter how many
waiter threads each parks, and a weight-2 client legitimately draws
twice the share.

Multi-tenant QoS (doc/tenancy.md) adds a second stride level ABOVE the
client level: tenants share the grant stream by tenant weight, and
clients share *within* their tenant by client weight.  A tenant
flooding from 100 requestor pids advances its single tenant pass 100x
as fast — exactly the isolation a per-client-only stride cannot give
once one org controls many pids.  The client key also stops being
globally meaningful with tenancy on (a bare PID collides across hosts
once delegates multiplex tenants), so the tenant string partitions the
client table: the PID stays the *within-tenant* key.  The default ""
tenant is the shared legacy level — a queue used without tenants
degenerates to the original single-level scheduler, same grants in the
same order.

Properties the tests assert (tests/test_robustness.py,
tests/test_tenancy.py):

  * with an adversary submitting at 10x, every other client still
    receives >= 80% of its equal share — and the same at the tenant
    level with an adversary tenant fanning out over many pids;
  * an idle client (or tenant) returning does NOT burst accumulated
    credit — its pass is clamped to the current virtual time on
    arrival;
  * no grant is lost: items offered while a waiter times out stay in
    the backlog for the next waiter.

One instance per env fetcher; the lock is a leaf (nothing is called
out of it).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class _Waiter:
    __slots__ = ("key", "weight", "item")

    def __init__(self, key: str, weight: float):
        self.key = key
        self.weight = weight
        self.item = None


class _Client:
    __slots__ = ("vpass", "waiters", "granted", "last_active")

    def __init__(self, vpass: float, now: float):
        self.vpass = vpass
        self.waiters: List[_Waiter] = []
        self.granted = 0
        self.last_active = now


class _Tenant:
    __slots__ = ("vpass", "weight", "clients", "vtime", "granted",
                 "last_active")

    def __init__(self, vpass: float, now: float):
        self.vpass = vpass
        self.weight = 1.0
        # Within-tenant client table + the tenant's own virtual time
        # (clients clamp against THEIR tenant's clock, not the global
        # one — a busy tenant must not launder credit to a client of an
        # idle tenant).
        self.clients: Dict[str, _Client] = {}
        self.vtime = vpass
        self.granted = 0
        self.last_active = now

    def has_waiters(self) -> bool:
        return any(c.waiters for c in self.clients.values())


class FairGrantQueue:
    """Weighted-fair item hand-out, tenant-then-client stride."""

    QUANTUM = 1024.0
    # Client/tenant records idle this long are dropped (their pass
    # history is clamped away on return anyway); bounds memory under
    # pid churn.
    CLIENT_TTL_S = 600.0

    def __init__(self, time_fn: Callable[[], float] = time.monotonic):
        self._time = time_fn
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._backlog: List = []  # guarded by: self._lock
        self._tenants: Dict[str, _Tenant] = {}  # guarded by: self._lock
        self._vtime = 0.0  # guarded by: self._lock
        self._closed = False  # guarded by: self._lock

    # -- producer ------------------------------------------------------------

    def put(self, item) -> None:
        with self._cond:
            self._backlog.append(item)
            self._match_locked()
            self._cond.notify_all()

    # -- consumer ------------------------------------------------------------

    def get(self, key: str = "", weight: float = 1.0,
            timeout_s: float = 10.0, tenant: str = "",
            tenant_weight: float = 1.0):
        """Block until this client is handed an item or the timeout
        lapses (returns None).  ``key`` identifies the client for
        within-tenant fairness; "" is a shared anonymous client.
        ``tenant`` selects the outer stride level; "" is the shared
        legacy tenant (single-level behavior)."""
        deadline = self._time() + timeout_s
        with self._cond:
            if self._closed:
                return None
            c = self._client_locked(tenant, tenant_weight, key)
            w = _Waiter(key, weight)
            c.waiters.append(w)
            # Registering may unblock OTHER waiters too (the backlog is
            # matched by fairness order, not arrival order): notify.
            self._match_locked()
            self._cond.notify_all()
            while w.item is None and not self._closed:
                remaining = deadline - self._time()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            if w.item is None:
                # Timed out: deregister.  A racing put() matched under
                # this same lock, so w.item is authoritative here.
                if w in c.waiters:
                    c.waiters.remove(w)
                return None
            return w.item

    # -- introspection / lifecycle -------------------------------------------

    def qsize(self) -> int:
        with self._cond:
            return len(self._backlog)

    def waiter_count(self) -> int:
        with self._cond:
            return sum(len(c.waiters)
                       for t in self._tenants.values()
                       for c in t.clients.values())

    def close(self) -> None:
        """Stop matching: waiters return None, and every item offered
        from now on stays in the backlog for drain().  Used at fetcher
        retirement so a fetch that lands AFTER retire() hands its
        grants back to the scheduler instead of to a late waiter of a
        dead fetcher (who should re-register on a fresh one)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def drain(self) -> list:
        """Remove and return every unclaimed item (fetcher retirement:
        the grants go back to the scheduler)."""
        with self._cond:
            items, self._backlog = self._backlog, []
            return items

    def share_counts(self) -> Dict[str, int]:
        """Grants handed out per client key since construction — the
        fairness-dispersion measurement the scenario harness reports.
        Clients of the legacy "" tenant keep their bare keys (the
        pre-tenancy shape every caller knows); tenant clients report
        as "tenant/key"."""
        with self._cond:
            out: Dict[str, int] = {}
            for tname, t in self._tenants.items():
                for k, c in t.clients.items():
                    if c.granted:
                        out[f"{tname}/{k}" if tname else k] = c.granted
            return out

    def tenant_share_counts(self) -> Dict[str, int]:
        """Grants per tenant ("" = the shared legacy tenant)."""
        with self._cond:
            return {name: t.granted for name, t in self._tenants.items()
                    if t.granted}

    # -- locked internals ----------------------------------------------------

    def _client_locked(self, tenant: str, tenant_weight: float,
                       key: str) -> _Client:
        now = self._time()
        t = self._tenants.get(tenant)
        if t is None:
            if len(self._tenants) > 64:
                for name in [name for name, tl in self._tenants.items()
                             if not tl.has_waiters()
                             and now - tl.last_active > self.CLIENT_TTL_S]:
                    del self._tenants[name]
            t = self._tenants[tenant] = _Tenant(self._vtime, now)
        else:
            # Returning idle tenant: clamp to current virtual time so
            # accumulated "credit" from sitting out cannot burst.
            t.vpass = max(t.vpass, self._vtime)
        # Weight is re-stamped per call: the directory (not this queue)
        # owns tenant policy, and a weight change takes effect on the
        # tenant's next ask.
        t.weight = tenant_weight
        t.last_active = now
        c = t.clients.get(key)
        if c is None:
            if len(t.clients) > 256:
                for k in [k for k, cl in t.clients.items()
                          if not cl.waiters
                          and now - cl.last_active > self.CLIENT_TTL_S]:
                    del t.clients[k]
            c = t.clients[key] = _Client(t.vtime, now)
        else:
            # Same clamp at the client level, against the TENANT clock.
            c.vpass = max(c.vpass, t.vtime)
        c.last_active = now
        return c

    def _match_locked(self) -> None:
        if self._closed:
            return
        while self._backlog:
            bt: Optional[_Tenant] = None
            for t in self._tenants.values():
                if t.has_waiters() and (bt is None or t.vpass < bt.vpass):
                    bt = t
            if bt is None:
                return
            best: Optional[_Client] = None
            for c in bt.clients.values():
                if c.waiters and (best is None or c.vpass < best.vpass):
                    best = c
            w = best.waiters.pop(0)
            w.item = self._backlog.pop(0)
            # Advance both clocks: the grant costs the tenant one
            # weighted quantum of the global stream and the client one
            # weighted quantum of the tenant's stream.
            self._vtime = bt.vpass
            bt.vtime = best.vpass
            bt.vpass += self.QUANTUM / max(bt.weight, 1e-6)
            best.vpass += self.QUANTUM / max(w.weight, 1e-6)
            best.granted += 1
            bt.granted += 1
