"""Per-client weighted-fair grant admission (delegate side).

The grant keeper used to hand grants to whichever waiter thread won the
``queue.Queue`` race — FIFO across *threads*, which under a `make -j500`
or an oversized-TU adversary is FIFO across *one client's* five hundred
threads: everyone else on the box starves.  This module replaces the
hand-out with stride scheduling over requestor keys: every client
carries a virtual pass; each grant goes to the waiting client with the
lowest pass, whose pass then advances by ``quantum / weight``.  Two
clients with equal weights therefore alternate no matter how many
waiter threads each parks, and a weight-2 client legitimately draws
twice the share.

Properties the tests assert (tests/test_robustness.py):

  * with an adversary submitting at 10x, every other client still
    receives >= 80% of its equal share;
  * an idle client returning does NOT burst accumulated credit — its
    pass is clamped to the queue's current virtual time on arrival;
  * no grant is lost: items offered while a waiter times out stay in
    the backlog for the next waiter.

One instance per env fetcher; the lock is a leaf (nothing is called
out of it).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class _Waiter:
    __slots__ = ("key", "weight", "item")

    def __init__(self, key: str, weight: float):
        self.key = key
        self.weight = weight
        self.item = None


class _Client:
    __slots__ = ("vpass", "waiters", "granted", "last_active")

    def __init__(self, vpass: float, now: float):
        self.vpass = vpass
        self.waiters: List[_Waiter] = []
        self.granted = 0
        self.last_active = now


class FairGrantQueue:
    """Weighted-fair item hand-out keyed by client string."""

    QUANTUM = 1024.0
    # Client records idle this long are dropped (their pass history is
    # clamped away on return anyway); bounds memory under pid churn.
    CLIENT_TTL_S = 600.0

    def __init__(self, time_fn: Callable[[], float] = time.monotonic):
        self._time = time_fn
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._backlog: List = []  # guarded by: self._lock
        self._clients: Dict[str, _Client] = {}  # guarded by: self._lock
        self._vtime = 0.0  # guarded by: self._lock
        self._closed = False  # guarded by: self._lock

    # -- producer ------------------------------------------------------------

    def put(self, item) -> None:
        with self._cond:
            self._backlog.append(item)
            self._match_locked()
            self._cond.notify_all()

    # -- consumer ------------------------------------------------------------

    def get(self, key: str = "", weight: float = 1.0,
            timeout_s: float = 10.0):
        """Block until this client is handed an item or the timeout
        lapses (returns None).  ``key`` identifies the client for
        fairness; "" is a shared anonymous client."""
        deadline = self._time() + timeout_s
        with self._cond:
            if self._closed:
                return None
            c = self._client_locked(key)
            w = _Waiter(key, weight)
            c.waiters.append(w)
            # Registering may unblock OTHER waiters too (the backlog is
            # matched by fairness order, not arrival order): notify.
            self._match_locked()
            self._cond.notify_all()
            while w.item is None and not self._closed:
                remaining = deadline - self._time()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            if w.item is None:
                # Timed out: deregister.  A racing put() matched under
                # this same lock, so w.item is authoritative here.
                if w in c.waiters:
                    c.waiters.remove(w)
                return None
            return w.item

    # -- introspection / lifecycle -------------------------------------------

    def qsize(self) -> int:
        with self._cond:
            return len(self._backlog)

    def waiter_count(self) -> int:
        with self._cond:
            return sum(len(c.waiters) for c in self._clients.values())

    def close(self) -> None:
        """Stop matching: waiters return None, and every item offered
        from now on stays in the backlog for drain().  Used at fetcher
        retirement so a fetch that lands AFTER retire() hands its
        grants back to the scheduler instead of to a late waiter of a
        dead fetcher (who should re-register on a fresh one)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def drain(self) -> list:
        """Remove and return every unclaimed item (fetcher retirement:
        the grants go back to the scheduler)."""
        with self._cond:
            items, self._backlog = self._backlog, []
            return items

    def share_counts(self) -> Dict[str, int]:
        """Grants handed out per client key since construction — the
        fairness-dispersion measurement the scenario harness reports."""
        with self._cond:
            return {k: c.granted for k, c in self._clients.items()
                    if c.granted}

    # -- locked internals ----------------------------------------------------

    def _client_locked(self, key: str) -> _Client:
        now = self._time()
        c = self._clients.get(key)
        if c is None:
            if len(self._clients) > 256:
                for k in [k for k, cl in self._clients.items()
                          if not cl.waiters
                          and now - cl.last_active > self.CLIENT_TTL_S]:
                    del self._clients[k]
            c = self._clients[key] = _Client(self._vtime, now)
        else:
            # Returning idle client: clamp to current virtual time so
            # accumulated "credit" from sitting out cannot burst.
            c.vpass = max(c.vpass, self._vtime)
        c.last_active = now
        return c

    def _match_locked(self) -> None:
        if self._closed:
            return
        while self._backlog:
            best: Optional[_Client] = None
            for c in self._clients.values():
                if c.waiters and (best is None or c.vpass < best.vpass):
                    best = c
            if best is None:
                return
            w = best.waiters.pop(0)
            w.item = self._backlog.pop(0)
            self._vtime = best.vpass
            best.vpass += self.QUANTUM / max(w.weight, 1e-6)
            best.granted += 1
