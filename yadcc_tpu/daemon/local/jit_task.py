"""Delegate-side XLA jit-compilation task.

The second DistributedTask implementation — proof that the dispatcher's
cache→join→dispatch state machine really is workload-agnostic: this
class supplies only the four task-specific ingredients (cache key,
dedup digest, servant submission RPC, output parsing) and inherits
cluster-wide dedup of identical in-flight compilations for free — the
thundering-herd case where N hosts jit the same model step at the same
moment compiles it exactly once (RunningTaskKeeper join on the task
digest, same as duplicate TUs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ... import api
from ...common.limits import checked_attachment
from ...jit.env import jit_env_digest
from .. import cache_format, packing
from ..cache_format import get_jit_cache_key
from ..task_digest import get_jit_task_digest
from .distributed_task import DistributedTask, TaskResult


class NeedJitEnvironment(Exception):
    """The submission names no jit environment (backend + jaxlib
    version); mapped to HTTP 400 on /local/submit_jit_task, after which
    the client fills in its local environment and retries — the
    NeedCompilerDigest pattern of the cxx route."""


@dataclass
class JitCompilationTask(DistributedTask):
    requestor_pid: int
    computation_digest: str
    compile_options: bytes
    backend: str
    jaxlib_version: str
    cache_control: int  # 0 off, 1 on, 2 = refill (skip reads, still fill)
    # bytes-like: zstd StableHLO, a view into the HTTP request body.
    compressed_computation: bytes

    kind = "jit"

    def get_cache_setting(self) -> int:
        if self.cache_control in (self.CACHE_DISALLOW, self.CACHE_ALLOW,
                                  self.CACHE_REFILL):
            return self.cache_control
        return self.CACHE_ALLOW

    @property
    def env_digest(self) -> str:
        return jit_env_digest(self.backend, self.jaxlib_version)

    def get_cache_key(self) -> Optional[str]:
        if self.get_cache_setting() == self.CACHE_DISALLOW:
            return None
        return get_jit_cache_key(self.env_digest, self.compile_options,
                                 self.computation_digest,
                                 tenant_secret=self.tenant_key_secret)

    def get_digest(self) -> str:
        return get_jit_task_digest(self.env_digest, self.compile_options,
                                   self.computation_digest)

    def get_env_digest(self) -> str:
        return self.env_digest

    def start_task(self, channel, token: str, grant_id: int) -> int:
        req = api.jit.QueueJitCompilationTaskRequest(
            token=token,
            task_grant_id=grant_id,
            computation_digest=self.computation_digest,
            compile_options=bytes(self.compile_options),
            backend=self.backend,
            compression_algorithm=api.daemon.COMPRESSION_ALGORITHM_ZSTD,
            disallow_cache_fill=self.cache_control <= 0,
        )
        req.env_desc.compiler_digest = self.env_digest
        req.env_desc.tenant_scope = self.tenant_key_secret
        resp, _ = channel.call(
            "ytpu.DaemonService", "QueueJitCompilationTask", req,
            api.jit.QueueJitCompilationTaskResponse,
            attachment=self.compressed_computation, timeout=30.0)
        return resp.task_id

    def parse_servant_output(self, resp, attachment) -> TaskResult:
        files = packing.try_unpack_keyed_buffers_views(attachment) or {}
        return TaskResult(
            exit_code=resp.exit_code,
            standard_output=resp.standard_output,
            standard_error=resp.standard_error,
            files=files,
        )

    def parse_cache_entry(self, data) -> Optional[TaskResult]:
        entry = cache_format.try_parse_cache_entry(
            data, expect_kind=cache_format.KIND_JIT)
        if entry is None:
            return None
        return TaskResult(
            exit_code=entry.exit_code,
            standard_output=entry.standard_output,
            standard_error=entry.standard_error,
            files=entry.files,
            from_cache=True,
        )


def make_jit_task(msg: "api.jit.SubmitJitTaskRequest",
                  compressed_computation: bytes) -> JitCompilationTask:
    """Build a task from the client's /local/submit_jit_task message;
    raises NeedJitEnvironment when the environment pair is missing —
    the delegate never guesses which XLA stack lowered the module."""
    if not msg.backend or not msg.jaxlib_version:
        raise NeedJitEnvironment(
            f"backend={msg.backend!r} jaxlib_version={msg.jaxlib_version!r}")
    if not msg.computation_digest:
        raise ValueError("computation_digest is required")
    return JitCompilationTask(
        requestor_pid=msg.requestor_process_id,
        computation_digest=msg.computation_digest,
        compile_options=msg.compile_options,
        backend=msg.backend,
        jaxlib_version=msg.jaxlib_version,
        cache_control=msg.cache_control,
        # Same wire-cap-at-intake contract as make_cxx_task.
        compressed_computation=checked_attachment(compressed_computation),
    )
