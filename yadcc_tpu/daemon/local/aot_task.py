"""Delegate-side AOT multi-topology build (workload 3).

One client submission carries a StableHLO module plus a list of
topology specs; the dispatcher's fan-out path (jit/fanout.py) expands
it into one ``AotTopologyCompilationTask`` per topology.  Each child is
a full DistributedTask — its own topology-tagged cache key
(``ytpu-aot1-``), its own dedup digest, its own grant — so the
cache→join→dispatch machinery gives partial-hit reuse for free: cached
topologies resolve from the distributed cache without a grant, and only
the misses fan out to servants.  The fleet-wide version of JAX's
persistent compile cache (PAPERS.md, Frostig et al.), with the
multi-topology sharded-build twist.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ... import api
from ...common.limits import checked_attachment
from ...jit import fanout
from ...jit.env import jit_env_digest
from .. import cache_format, packing
from ..cache_format import get_aot_cache_key
from ..task_digest import get_aot_task_digest
from .distributed_task import DistributedTask, TaskResult
from .jit_task import NeedJitEnvironment

# The one artifact key a topology child's servant packs (the
# serialized executable; see daemon/cloud/jit_task.py ARTIFACT_KEY —
# kept as a literal to avoid a local->cloud import).
_CHILD_ARTIFACT_KEY = ".xla"


@dataclass
class AotTopologyCompilationTask(DistributedTask):
    """One fan-out CHILD: compile the parent's module for exactly one
    topology.  Mirrors JitCompilationTask with the topology folded
    into every identity (digest, cache key, servant request)."""

    requestor_pid: int
    computation_digest: str
    backend: str
    jaxlib_version: str
    cache_control: int
    topology: fanout.TopologySpec
    # bytes-like: zstd StableHLO — a view shared with the parent (and
    # its sibling children); never copied per child.
    compressed_computation: bytes

    kind = "aot"

    def get_cache_setting(self) -> int:
        if self.cache_control in (self.CACHE_DISALLOW, self.CACHE_ALLOW,
                                  self.CACHE_REFILL):
            return self.cache_control
        return self.CACHE_ALLOW

    @property
    def env_digest(self) -> str:
        return jit_env_digest(self.backend, self.jaxlib_version)

    def get_cache_key(self) -> Optional[str]:
        if self.get_cache_setting() == self.CACHE_DISALLOW:
            return None
        return get_aot_cache_key(self.env_digest, self.topology.digest(),
                                 self.computation_digest,
                                 tenant_secret=self.tenant_key_secret)

    def get_digest(self) -> str:
        return get_aot_task_digest(self.env_digest,
                                   self.topology.digest(),
                                   self.computation_digest)

    def get_env_digest(self) -> str:
        return self.env_digest

    def start_task(self, channel, token: str, grant_id: int) -> int:
        req = api.fanout.QueueAotCompilationTaskRequest(
            token=token,
            task_grant_id=grant_id,
            computation_digest=self.computation_digest,
            backend=self.backend,
            compression_algorithm=api.daemon.COMPRESSION_ALGORITHM_ZSTD,
            disallow_cache_fill=self.cache_control <= 0,
        )
        req.env_desc.compiler_digest = self.env_digest
        req.env_desc.tenant_scope = self.tenant_key_secret
        req.topology.mesh_shape.extend(self.topology.mesh_shape)
        req.topology.device_count = self.topology.device_count
        req.topology.compile_options = bytes(
            self.topology.compile_options)
        resp, _ = channel.call(
            "ytpu.DaemonService", "QueueAotCompilationTask", req,
            api.fanout.QueueAotCompilationTaskResponse,
            attachment=self.compressed_computation, timeout=30.0)
        return resp.task_id

    def parse_servant_output(self, resp, attachment) -> TaskResult:
        files = packing.try_unpack_keyed_buffers_views(attachment) or {}
        return TaskResult(
            exit_code=resp.exit_code,
            standard_output=resp.standard_output,
            standard_error=resp.standard_error,
            files=files,
        )

    def parse_cache_entry(self, data) -> Optional[TaskResult]:
        entry = cache_format.try_parse_cache_entry(
            data, expect_kind=cache_format.KIND_AOT)
        if entry is None:
            return None
        return TaskResult(
            exit_code=entry.exit_code,
            standard_output=entry.standard_output,
            standard_error=entry.standard_error,
            files=entry.files,
            from_cache=True,
        )


@dataclass
class AotBuildTask(DistributedTask):
    """The fan-out PARENT: never touches a servant itself — it expands
    into topology children, joins them, and reduces their artifacts
    into one topology-keyed result with explicit per-child verdicts."""

    requestor_pid: int
    computation_digest: str
    backend: str
    jaxlib_version: str
    cache_control: int
    topologies: List[fanout.TopologySpec]
    compressed_computation: bytes

    kind = "aot"
    is_fanout = True

    def get_cache_setting(self) -> int:
        if self.cache_control in (self.CACHE_DISALLOW, self.CACHE_ALLOW,
                                  self.CACHE_REFILL):
            return self.cache_control
        return self.CACHE_ALLOW

    def get_cache_key(self) -> Optional[str]:
        # No parent-level entry: the unit of caching is the topology
        # (that is what makes partial hits possible at all).
        return None

    def get_digest(self) -> str:
        # Diagnostics only — parents are never deduped as a unit; the
        # children carry the cluster-wide dedup.
        return get_aot_task_digest(
            jit_env_digest(self.backend, self.jaxlib_version),
            fanout.slice_digest([t.digest() for t in self.topologies]),
            self.computation_digest)

    def get_env_digest(self) -> str:
        return jit_env_digest(self.backend, self.jaxlib_version)

    def parse_cache_entry(self, data) -> Optional[TaskResult]:
        return None

    # -- fan-out SPI ---------------------------------------------------------

    def expand_children(self) -> List[Tuple[str, DistributedTask]]:
        fanout.checked_fanout_width(len(self.topologies))
        children: List[Tuple[str, DistributedTask]] = []
        for topo in self.topologies:
            children.append((topo.tag(), AotTopologyCompilationTask(
                requestor_pid=self.requestor_pid,
                computation_digest=self.computation_digest,
                backend=self.backend,
                jaxlib_version=self.jaxlib_version,
                cache_control=self.cache_control,
                topology=topo,
                compressed_computation=self.compressed_computation,
            )))
        fanout.split_fairness(self, [c for _, c in children])
        return children

    def reduce(self, outcomes: Dict[str, fanout.ChildOutcome]
               ) -> TaskResult:
        files: Dict[str, bytes] = {}
        for key, outcome in outcomes.items():
            result = outcome.result
            if result is not None and result.exit_code == 0:
                artifact = result.files.get(_CHILD_ARTIFACT_KEY)
                if artifact is not None:
                    files[f".{key}.xla"] = artifact
        code = fanout.aggregate_exit_code(outcomes)
        return TaskResult(
            exit_code=code,
            standard_output=fanout.verdict_summary(outcomes).encode(),
            standard_error=(b"" if code == 0 else
                            b"aot fan-out completed with failures: "
                            + fanout.verdict_summary(outcomes).encode()),
            files=files,
            verdicts=[o.verdict for o in outcomes.values()],
        )


def make_aot_task(msg: "api.fanout.SubmitAotTaskRequest",
                  compressed_computation: bytes) -> AotBuildTask:
    """Build the fan-out parent from /local/submit_aot_task; raises
    NeedJitEnvironment (HTTP 400, report-and-retry) when the
    environment pair is missing, ValueError on a malformed topology
    list or an over-wide fan-out."""
    if not msg.backend or not msg.jaxlib_version:
        raise NeedJitEnvironment(
            f"backend={msg.backend!r} jaxlib_version={msg.jaxlib_version!r}")
    if not msg.computation_digest:
        raise ValueError("computation_digest is required")
    topologies = [
        fanout.TopologySpec(
            mesh_shape=tuple(t.mesh_shape),
            device_count=t.device_count,
            compile_options=bytes(t.compile_options),
        ).validate()
        for t in msg.topologies
    ]
    fanout.checked_fanout_width(len(topologies))
    if len({t.digest() for t in topologies}) != len(topologies):
        raise ValueError("duplicate topology in submission")
    return AotBuildTask(
        requestor_pid=msg.requestor_process_id,
        computation_digest=msg.computation_digest,
        backend=msg.backend,
        jaxlib_version=msg.jaxlib_version,
        cache_control=msg.cache_control,
        topologies=topologies,
        # Same wire-cap-at-intake contract as make_cxx_task.
        compressed_computation=checked_attachment(compressed_computation),
    )
