"""Cluster-wide running-task snapshot for duplicate-compilation joining.

Parity with reference yadcc/daemon/local/running_task_keeper.h:33-58:
periodically pulls the scheduler's merged running-task list; a delegate
about to compile digest D first checks whether some servant is already
compiling D and joins that task instead.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional

from ... import api
from ...rpc import Channel, RpcError
from ...utils.logging import get_logger

logger = get_logger("daemon.running_task_keeper")


@dataclass(frozen=True)
class FoundTask:
    servant_location: str
    servant_task_id: int


class RunningTaskKeeper:
    def __init__(self, scheduler_uri: str, refresh_interval_s: float = 5.0):
        self._uri = scheduler_uri
        self._interval = refresh_interval_s
        self._lock = threading.Lock()
        self._by_digest: Dict[str, FoundTask] = {}  # guarded by: self._lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._channel: Optional[Channel] = None  # guarded by: self._lock

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name="running-task-keeper",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def try_find_task(self, digest: str) -> Optional[FoundTask]:
        with self._lock:
            return self._by_digest.get(digest)

    def refresh_once(self) -> None:
        try:
            resp, _ = self._chan().call(
                "ytpu.SchedulerService", "GetRunningTasks",
                api.scheduler.GetRunningTasksRequest(),
                api.scheduler.GetRunningTasksResponse, timeout=5.0)
            table = {
                t.task_digest: FoundTask(t.servant_location,
                                         t.servant_task_id)
                for t in resp.running_tasks if t.task_digest
            }
            with self._lock:
                self._by_digest = table
        except RpcError as e:
            logger.warning("GetRunningTasks failed: %s", e)

    def _chan(self) -> Channel:
        with self._lock:
            if self._channel is None:
                self._channel = Channel(self._uri)
            return self._channel

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self._interval):
            self.refresh_once()
