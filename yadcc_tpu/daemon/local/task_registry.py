"""Task-type registry: route / task-kind → DistributedTask wiring.

Before the second workload landed, the HTTP service hardcoded the cxx
submit/wait routes and their message classes; opening workload N+1
meant forking that routing.  Now each task kind contributes one
``TaskType`` row — routes, request classes, the factory that turns a
parsed submission into a DistributedTask, and the wait-response shaper
— and the HTTP layer drives every kind through the same generic
submit/wait flow.  The third workload is literally a dict entry.

All submit routes share the wire shape (multi-chunk [JSON, attachment])
and all wait routes share the long-poll semantics (503 running, 404
unknown, 200 multi-chunk [JSON, output chunks...]); what varies is the
message vocabulary and the task construction — exactly what a TaskType
captures.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ... import api
from ...common import compress
from .aot_task import make_aot_task
from .autotune_task import WINNER_RECORD_KEY, make_autotune_task
from .cxx_task import NeedCompilerDigest, make_cxx_task
from .distributed_task import DistributedTask, TaskResult
from .jit_task import NeedJitEnvironment, make_jit_task


@dataclass(frozen=True)
class TaskType:
    kind: str
    submit_route: str
    wait_route: str
    submit_request_cls: type
    wait_request_cls: type
    # (parsed submit message, attachment view) -> task; may raise —
    # exceptions are mapped to HTTP 400 via `submit_error`.
    make_task: Callable[[object, bytes], DistributedTask]
    # result -> (wait-response proto message, ordered output chunks).
    build_wait_response: Callable[[TaskResult], Tuple[object, List[bytes]]]
    # Known-bad-submission mapper: exception -> 400 body, or None to
    # treat the exception as an internal error (HTTP 500).
    submit_error: Callable[[Exception], Optional[bytes]]
    # 400 body when the multi-chunk framing is missing/miscounted.
    bad_chunks_error: bytes


class TaskTypeRegistry:
    """Immutable-after-construction lookup tables; no locking needed —
    built once at service construction, read-only afterwards."""

    def __init__(self, types: List[TaskType]):
        self._by_submit: Dict[str, TaskType] = {}
        self._by_wait: Dict[str, TaskType] = {}
        for t in types:
            if t.submit_route in self._by_submit or \
                    t.wait_route in self._by_wait:
                raise ValueError(f"duplicate route for kind {t.kind!r}")
            self._by_submit[t.submit_route] = t
            self._by_wait[t.wait_route] = t

    def for_submit(self, path: str) -> Optional[TaskType]:
        return self._by_submit.get(path)

    def for_wait(self, path: str) -> Optional[TaskType]:
        return self._by_wait.get(path)

    def kinds(self) -> List[str]:
        return sorted(t.kind for t in self._by_submit.values())


# -- the two workloads -------------------------------------------------------


def _cxx_wait_response(result: TaskResult) -> Tuple[object, List[bytes]]:
    resp = api.local.WaitForCxxTaskResponse(
        exit_code=result.exit_code,
        output=result.standard_output.decode(errors="replace"),
        error=result.standard_error.decode(errors="replace"),
    )
    chunks: List[bytes] = []
    for key in sorted(result.files):
        resp.file_extensions.append(key)
        pl = resp.patches.add(file_key=key)
        for pos, total, suffix in result.patches.get(key, []):
            pl.locations.add(position=pos, total_size=total,
                             suffix_to_keep=suffix)
        chunks.append(result.files[key])
    return resp, chunks


def _cxx_submit_error(e: Exception) -> Optional[bytes]:
    if isinstance(e, NeedCompilerDigest):
        return (b'{"error":"compiler digest unknown; '
                b'set_file_digest first"}')
    return None


def _jit_wait_response(result: TaskResult) -> Tuple[object, List[bytes]]:
    resp = api.jit.WaitForJitTaskResponse(
        exit_code=result.exit_code,
        output=result.standard_output.decode(errors="replace"),
        error=result.standard_error.decode(errors="replace"),
    )
    chunks: List[bytes] = []
    for key in sorted(result.files):
        resp.artifact_keys.append(key)
        chunks.append(result.files[key])
    return resp, chunks


def _jit_submit_error(e: Exception) -> Optional[bytes]:
    if isinstance(e, NeedJitEnvironment):
        return (b'{"error":"jit environment unknown; supply backend '
                b'and jaxlib_version"}')
    if isinstance(e, ValueError):
        return b'{"error":"invalid jit submission"}'
    return None


def _fanout_verdicts_into(resp, result: TaskResult) -> None:
    for v in result.verdicts:
        resp.verdicts.add(child_key=v.child_key, status=v.status,
                          exit_code=v.exit_code, attempts=v.attempts,
                          error=v.error)


def _aot_wait_response(result: TaskResult) -> Tuple[object, List[bytes]]:
    resp = api.fanout.WaitForAotTaskResponse(
        exit_code=result.exit_code,
        output=result.standard_output.decode(errors="replace"),
        error=result.standard_error.decode(errors="replace"),
    )
    _fanout_verdicts_into(resp, result)
    chunks: List[bytes] = []
    for key in sorted(result.files):
        resp.artifact_keys.append(key)
        chunks.append(result.files[key])
    return resp, chunks


def _autotune_wait_response(result: TaskResult
                            ) -> Tuple[object, List[bytes]]:
    resp = api.fanout.WaitForAutotuneTaskResponse(
        exit_code=result.exit_code,
        output=result.standard_output.decode(errors="replace"),
        error=result.standard_error.decode(errors="replace"),
    )
    _fanout_verdicts_into(resp, result)
    winner = result.files.get(WINNER_RECORD_KEY)
    if winner is not None:
        raw = compress.try_decompress(bytes(winner))
        if raw is not None:
            resp.winner_config_json = raw.decode(errors="replace")
    chunks: List[bytes] = []
    for key in sorted(result.files):
        resp.artifact_keys.append(key)
        chunks.append(result.files[key])
    return resp, chunks


def _fanout_submit_error(e: Exception) -> Optional[bytes]:
    if isinstance(e, NeedJitEnvironment):
        return (b'{"error":"jit environment unknown; supply backend '
                b'and jaxlib_version"}')
    if isinstance(e, ValueError):
        return b'{"error":"invalid fan-out submission"}'
    return None


def default_registry(digest_cache) -> TaskTypeRegistry:
    """The production registry: cxx (compiler digests resolved through
    the FileDigestCache) + jit + the two fan-out kinds (aot multi-
    topology builds, autotune sweeps — doc/workloads.md)."""
    return TaskTypeRegistry([
        TaskType(
            kind="cxx",
            submit_route="/local/submit_cxx_task",
            wait_route="/local/wait_for_cxx_task",
            submit_request_cls=api.local.SubmitCxxTaskRequest,
            wait_request_cls=api.local.WaitForCxxTaskRequest,
            make_task=lambda msg, att: make_cxx_task(
                msg, att, digest_cache),
            build_wait_response=_cxx_wait_response,
            submit_error=_cxx_submit_error,
            bad_chunks_error=b'{"error":"expect json+source chunks"}',
        ),
        TaskType(
            kind="jit",
            submit_route="/local/submit_jit_task",
            wait_route="/local/wait_for_jit_task",
            submit_request_cls=api.jit.SubmitJitTaskRequest,
            wait_request_cls=api.jit.WaitForJitTaskRequest,
            make_task=lambda msg, att: make_jit_task(msg, att),
            build_wait_response=_jit_wait_response,
            submit_error=_jit_submit_error,
            bad_chunks_error=b'{"error":"expect json+stablehlo chunks"}',
        ),
        TaskType(
            kind="aot",
            submit_route="/local/submit_aot_task",
            wait_route="/local/wait_for_aot_task",
            submit_request_cls=api.fanout.SubmitAotTaskRequest,
            wait_request_cls=api.fanout.WaitForAotTaskRequest,
            make_task=lambda msg, att: make_aot_task(msg, att),
            build_wait_response=_aot_wait_response,
            submit_error=_fanout_submit_error,
            bad_chunks_error=b'{"error":"expect json+stablehlo chunks"}',
        ),
        TaskType(
            kind="autotune",
            submit_route="/local/submit_autotune_task",
            wait_route="/local/wait_for_autotune_task",
            submit_request_cls=api.fanout.SubmitAutotuneTaskRequest,
            wait_request_cls=api.fanout.WaitForAutotuneTaskRequest,
            make_task=lambda msg, att: make_autotune_task(msg, att),
            build_wait_response=_autotune_wait_response,
            submit_error=_fanout_submit_error,
            bad_chunks_error=b'{"error":"expect json+kernel chunks"}',
        ),
    ])
