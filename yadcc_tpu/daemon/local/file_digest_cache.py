"""(path, size, mtime) -> digest memo.

Parity with reference yadcc/daemon/local/file_digest_cache.h:29-70: the
daemon may not have read permission on the client's compiler binary, so
the *client* digests it and reports the result; the daemon memoizes it
against the file's cheap identity attributes.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple


class FileDigestCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._memo: Dict[Tuple[str, int, int], str] = {}

    def set(self, path: str, size: int, mtime: int, digest: str) -> None:
        with self._lock:
            self._memo[(path, size, mtime)] = digest

    def try_get(self, path: str, size: int, mtime: int) -> Optional[str]:
        with self._lock:
            return self._memo.get((path, size, mtime))

    def inspect(self) -> dict:
        with self._lock:
            return {"entries": len(self._memo)}
