"""(path, size, mtime) -> digest memo.

Parity with reference yadcc/daemon/local/file_digest_cache.h:29-70: the
daemon may not have read permission on the client's compiler binary, so
the *client* digests it and reports the result; the daemon memoizes it
against the file's cheap identity attributes.

Unlike the reference (whose test build runs under gperftools
heap_check='strict', BLADE_ROOT:25-33), a long-running Python daemon
gets no allocator-level leak tier — so this map is explicitly bounded:
keys are client-supplied (any path x size x mtime), and an unbounded
memo would be a slow memory leak driven by toolchain updates or a
misbehaving client.  LRU eviction; the cap is far above any real
machine's toolchain count.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

DEFAULT_CAPACITY = 65536


class FileDigestCache:
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._capacity = max(1, capacity)
        self._memo: "OrderedDict[Tuple[str, int, int], str]" = \
            OrderedDict()  # guarded by: self._lock

    def set(self, path: str, size: int, mtime: int, digest: str) -> None:
        with self._lock:
            key = (path, size, mtime)
            self._memo[key] = digest
            self._memo.move_to_end(key)
            while len(self._memo) > self._capacity:
                self._memo.popitem(last=False)

    def try_get(self, path: str, size: int, mtime: int) -> Optional[str]:
        with self._lock:
            digest = self._memo.get((path, size, mtime))
            if digest is not None:
                self._memo.move_to_end((path, size, mtime))
            return digest

    def inspect(self) -> dict:
        with self._lock:
            return {"entries": len(self._memo),
                    "capacity": self._capacity}
