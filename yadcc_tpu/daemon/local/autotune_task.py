"""Delegate-side Pallas/autotune sweep (workload 4).

One client submission carries a kernel plus a candidate config list
(block/grid parameters); the fan-out path slices the list into child
sweeps, each evaluated servant-side.  The cached artifact — at both
levels — is a *winning config record* (JSON: config, score, metric),
never an executable:

  * each CHILD caches its slice's winner under
    (env, slice digest, kernel digest) in ``ytpu-tune1-``;
  * the PARENT, after reducing slice winners to the sweep winner,
    fills a SWEEP-level entry under (env, search-space digest, kernel
    digest) through the delegate's cache writer — so a second host
    sweeping the identical space gets the final answer in ONE cache
    read, with zero fan-out and zero servant time.

A record is tiny and environment-keyed, which is what makes it safe to
share cluster-wide: the measurement machine and the consuming machine
agree on (backend, jaxlib) by construction.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ... import api
from ...common import compress
from ...common.limits import checked_attachment
from ...common.payload import Payload
from ...jit import fanout
from ...jit.env import jit_env_digest
from .. import cache_format, packing
from ..cache_format import (
    CacheEntry,
    get_autotune_cache_key,
    get_autotune_sweep_key,
)
from ..task_digest import get_autotune_task_digest
from .distributed_task import DistributedTask, TaskResult
from .jit_task import NeedJitEnvironment

# The one artifact key a slice child produces (its winner record) and
# the parent's reduced artifact key (the sweep winner record).
SLICE_RECORD_KEY = ".cfg"
WINNER_RECORD_KEY = ".winner"


def parse_winner_record(compressed: bytes) -> Optional[dict]:
    """Decode one (zstd) winner-record artifact; None on any
    corruption — records cross the cache, so a bad one must read as
    a miss, not raise into the reduce."""
    raw = compress.try_decompress(bytes(compressed))
    if raw is None:
        return None
    try:
        record = json.loads(raw)
    except ValueError:
        return None
    if not isinstance(record, dict) or "config" not in record \
            or "score" not in record:
        return None
    return record


@dataclass
class AutotuneSliceTask(DistributedTask):
    """One fan-out CHILD: evaluate a contiguous slice of the candidate
    configs on a servant and return the slice's winner record."""

    requestor_pid: int
    kernel_digest: str
    backend: str
    jaxlib_version: str
    cache_control: int
    configs: List[str]  # canonical-JSON candidate configs (the slice)
    # bytes-like: zstd kernel source, shared with the parent.
    compressed_kernel: bytes

    kind = "autotune"

    def get_cache_setting(self) -> int:
        if self.cache_control in (self.CACHE_DISALLOW, self.CACHE_ALLOW,
                                  self.CACHE_REFILL):
            return self.cache_control
        return self.CACHE_ALLOW

    @property
    def env_digest(self) -> str:
        return jit_env_digest(self.backend, self.jaxlib_version)

    @property
    def slice_digest(self) -> str:
        return fanout.slice_digest(self.configs)

    def get_cache_key(self) -> Optional[str]:
        if self.get_cache_setting() == self.CACHE_DISALLOW:
            return None
        return get_autotune_cache_key(self.env_digest, self.slice_digest,
                                      self.kernel_digest,
                                      tenant_secret=self.tenant_key_secret)

    def get_digest(self) -> str:
        return get_autotune_task_digest(self.env_digest,
                                        self.slice_digest,
                                        self.kernel_digest)

    def get_env_digest(self) -> str:
        return self.env_digest

    def start_task(self, channel, token: str, grant_id: int) -> int:
        req = api.fanout.QueueAutotuneTaskRequest(
            token=token,
            task_grant_id=grant_id,
            kernel_digest=self.kernel_digest,
            backend=self.backend,
            compression_algorithm=api.daemon.COMPRESSION_ALGORITHM_ZSTD,
            disallow_cache_fill=self.cache_control <= 0,
        )
        req.env_desc.compiler_digest = self.env_digest
        req.env_desc.tenant_scope = self.tenant_key_secret
        req.configs.extend(self.configs)
        resp, _ = channel.call(
            "ytpu.DaemonService", "QueueAutotuneTask", req,
            api.fanout.QueueAutotuneTaskResponse,
            attachment=self.compressed_kernel, timeout=30.0)
        return resp.task_id

    def parse_servant_output(self, resp, attachment) -> TaskResult:
        files = packing.try_unpack_keyed_buffers_views(attachment) or {}
        return TaskResult(
            exit_code=resp.exit_code,
            standard_output=resp.standard_output,
            standard_error=resp.standard_error,
            files=files,
        )

    def parse_cache_entry(self, data) -> Optional[TaskResult]:
        entry = cache_format.try_parse_cache_entry(
            data, expect_kind=cache_format.KIND_AUTOTUNE)
        if entry is None:
            return None
        return TaskResult(
            exit_code=entry.exit_code,
            standard_output=entry.standard_output,
            standard_error=entry.standard_error,
            files=entry.files,
            from_cache=True,
        )


@dataclass
class AutotuneSweepTask(DistributedTask):
    """The fan-out PARENT: slices the space, joins the slice winners,
    reduces to the sweep winner — and is itself cacheable at the
    sweep level (the one fan-out parent with a cache identity)."""

    requestor_pid: int
    kernel_digest: str
    backend: str
    jaxlib_version: str
    cache_control: int
    configs: List[str]  # the WHOLE candidate list, canonical JSON
    fanout_width: int   # validated child count (>=1)
    compressed_kernel: bytes

    kind = "autotune"
    is_fanout = True

    def get_cache_setting(self) -> int:
        if self.cache_control in (self.CACHE_DISALLOW, self.CACHE_ALLOW,
                                  self.CACHE_REFILL):
            return self.cache_control
        return self.CACHE_ALLOW

    @property
    def env_digest(self) -> str:
        return jit_env_digest(self.backend, self.jaxlib_version)

    @property
    def space_digest(self) -> str:
        return fanout.search_space_digest(self.configs)

    def get_cache_key(self) -> Optional[str]:
        if self.get_cache_setting() == self.CACHE_DISALLOW:
            return None
        return get_autotune_sweep_key(self.env_digest, self.space_digest,
                                      self.kernel_digest,
                                      tenant_secret=self.tenant_key_secret)

    def get_digest(self) -> str:
        return get_autotune_task_digest(self.env_digest,
                                        self.space_digest,
                                        self.kernel_digest)

    def get_env_digest(self) -> str:
        return self.env_digest

    def parse_cache_entry(self, data) -> Optional[TaskResult]:
        """A sweep-level hit: the final winner record, no fan-out."""
        entry = cache_format.try_parse_cache_entry(
            data, expect_kind=cache_format.KIND_AUTOTUNE)
        if entry is None:
            return None
        record = entry.files.get(WINNER_RECORD_KEY)
        if record is None or parse_winner_record(record) is None:
            return None  # a slice entry (or garbage) is not a verdict
        return TaskResult(
            exit_code=entry.exit_code,
            standard_output=entry.standard_output,
            standard_error=entry.standard_error,
            files={WINNER_RECORD_KEY: record},
            from_cache=True,
        )

    # -- fan-out SPI ---------------------------------------------------------

    def expand_children(self) -> List[Tuple[str, DistributedTask]]:
        width = fanout.checked_fanout_width(self.fanout_width)
        slices = fanout.slice_configs(self.configs, width)
        children: List[Tuple[str, DistributedTask]] = []
        for i, sl in enumerate(slices):
            key = f"s{i}-{fanout.slice_digest(sl)[:8]}"
            children.append((key, AutotuneSliceTask(
                requestor_pid=self.requestor_pid,
                kernel_digest=self.kernel_digest,
                backend=self.backend,
                jaxlib_version=self.jaxlib_version,
                cache_control=self.cache_control,
                configs=sl,
                compressed_kernel=self.compressed_kernel,
            )))
        fanout.split_fairness(self, [c for _, c in children])
        return children

    def reduce(self, outcomes: Dict[str, fanout.ChildOutcome]
               ) -> TaskResult:
        best: Optional[dict] = None
        evaluated = 0
        for outcome in outcomes.values():
            result = outcome.result
            if result is None or result.exit_code != 0:
                continue
            record = parse_winner_record(
                result.files.get(SLICE_RECORD_KEY, b""))
            if record is None:
                continue
            evaluated += int(record.get("evaluated", 0))
            if best is None or record["score"] > best["score"]:
                best = record
        code = fanout.aggregate_exit_code(outcomes)
        if best is None and code == 0:
            # Every child "succeeded" yet none produced a record:
            # corrupt records are an infra outcome, not a win.
            code = -1
        files: Dict[str, bytes] = {}
        if best is not None:
            winner = dict(best, evaluated=evaluated)
            files[WINNER_RECORD_KEY] = compress.compress(
                json.dumps(winner, sort_keys=True).encode())
        return TaskResult(
            exit_code=code,
            standard_output=fanout.verdict_summary(outcomes).encode(),
            standard_error=(b"" if code == 0 else
                            b"autotune fan-out completed with failures: "
                            + fanout.verdict_summary(outcomes).encode()),
            files=files,
            verdicts=[o.verdict for o in outcomes.values()],
        )

    def make_parent_cache_entry(self, result: TaskResult
                                ) -> Optional[Tuple[str, Payload]]:
        """The sweep-level fill (delegate-side, after reduce): only a
        fully-successful sweep may publish a winner — a partial sweep's
        'best so far' under the full-space key would lie to every
        future reader."""
        if result.exit_code != 0 or result.from_cache:
            return None
        if self.get_cache_setting() == self.CACHE_DISALLOW:
            return None
        record = result.files.get(WINNER_RECORD_KEY)
        if record is None:
            return None
        key = get_autotune_sweep_key(self.env_digest, self.space_digest,
                                     self.kernel_digest)
        entry = CacheEntry(
            exit_code=0,
            standard_output=b"",
            standard_error=b"",
            files={WINNER_RECORD_KEY: bytes(record)},
            kind=cache_format.KIND_AUTOTUNE,
        )
        return key, cache_format.write_cache_entry_payload(entry)


def make_autotune_task(msg: "api.fanout.SubmitAutotuneTaskRequest",
                       compressed_kernel: bytes) -> AutotuneSweepTask:
    """Build the sweep parent from /local/submit_autotune_task; raises
    NeedJitEnvironment when the environment pair is missing, ValueError
    on an empty/malformed config list or an over-wide fan-out."""
    if not msg.backend or not msg.jaxlib_version:
        raise NeedJitEnvironment(
            f"backend={msg.backend!r} jaxlib_version={msg.jaxlib_version!r}")
    if not msg.kernel_digest:
        raise ValueError("kernel_digest is required")
    configs = list(msg.configs)
    if not configs:
        raise ValueError("empty config search space")
    for c in configs:
        try:
            parsed = json.loads(c)
        except ValueError:
            parsed = None
        if not isinstance(parsed, dict):
            raise ValueError(f"config is not a JSON object: {c[:80]!r}")
    width = msg.fanout_width or min(len(configs),
                                    fanout.DEFAULT_AUTOTUNE_WIDTH)
    width = min(width, len(configs))
    fanout.checked_fanout_width(width)
    return AutotuneSweepTask(
        requestor_pid=msg.requestor_process_id,
        kernel_digest=msg.kernel_digest,
        backend=msg.backend,
        jaxlib_version=msg.jaxlib_version,
        cache_control=msg.cache_control,
        configs=configs,
        fanout_width=width,
        # Same wire-cap-at-intake contract as make_cxx_task.
        compressed_kernel=checked_attachment(compressed_kernel),
    )
