"""Delegate-side distributed cache reader with a local Bloom replica.

Parity with reference yadcc/daemon/local/distributed_cache_reader.h:32-56:
the daemon keeps a replica of the cache server's Bloom filter, synced
incrementally (new keys) with a jittered ~10-minute full refetch, and
TryRead() short-circuits guaranteed misses locally so cold builds don't
pay a network round trip per TU.

TPU path: when a batch of keys needs testing at once (burst submits,
the benchmark sweep), the replica's word array is probed on-device via
ops/bloom_probe.py — see batch_may_contain().
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional

from ... import api
from ...common import bloom, compress
from ...rpc import Channel, RpcError
from ...utils.logging import get_logger

logger = get_logger("daemon.cache_reader")

_FULL_FETCH_INTERVAL_S = 600.0  # ~10min, jittered per client
_SYNC_INTERVAL_S = 10.0


class DistributedCacheReader:
    def __init__(self, cache_server_uri: str, token: str):
        self._uri = cache_server_uri
        self._token = token
        self._lock = threading.Lock()
        # Learned from each full fetch (rides the payload); paired with
        # _filter — they must only ever be read together under the lock
        # (a full fetch replaces both; a torn read probes the new words
        # with the old salt and returns garbage membership).
        self._salt = 0  # guarded by: self._lock
        self._filter: Optional[bloom.SaltedBloomFilter] = \
            None  # guarded by: self._lock
        self._last_full_fetch = 0.0  # guarded by: self._lock
        self._last_fetch = 0.0  # guarded by: self._lock
        self._full_interval = _FULL_FETCH_INTERVAL_S * random.uniform(0.9, 1.1)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._channel: Optional[Channel] = None  # guarded by: self._lock
        self.hits = 0  # guarded by: self._lock
        self.bloom_rejects = 0  # guarded by: self._lock
        self.misses = 0  # guarded by: self._lock

    @property
    def enabled(self) -> bool:
        return bool(self._uri)

    def start(self) -> None:
        if not self.enabled:
            return
        self.sync_once()
        self._thread = threading.Thread(target=self._loop,
                                        name="bloom-sync", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    # -- reads ---------------------------------------------------------------

    def try_read(self, key: str) -> Optional[bytes]:
        """None on miss (including Bloom-filtered definite misses)."""
        if not self.enabled:
            return None
        with self._lock:
            flt = self._filter
        if flt is not None and not flt.may_contain(key):
            with self._lock:
                self.bloom_rejects += 1
            return None
        try:
            _, value = self._chan().call(
                "ytpu.CacheService", "TryGetEntry",
                api.cache.TryGetEntryRequest(token=self._token, key=key),
                api.cache.TryGetEntryResponse, timeout=5.0)
            with self._lock:
                self.hits += 1
            return value
        except RpcError:
            with self._lock:
                self.misses += 1
            return None

    def batch_may_contain(self, keys: List[str]):
        """Device-side batch Bloom test; numpy bool array (all-True when
        no filter is synced yet — absence of evidence isn't a miss).

        Rides the fused fingerprint→probe pipeline: the replica's raw
        key bytes go up once and one bool[N] comes back — no host
        hashing, no [N, 2] fingerprint upload (ops/bloom_pipeline.py)."""
        import numpy as np

        # Snapshot filter AND salt under one lock hold: a concurrent
        # full fetch swaps both, and probing new words with the old
        # salt (or vice versa) yields wrong membership answers — found
        # by ytpu-analyze (guarded-by) when _salt gained its annotation.
        with self._lock:
            flt = self._filter
            salt = self._salt
        if flt is None or not keys:
            return np.ones(len(keys), bool)
        import jax.numpy as jnp

        from ...ops.bloom_pipeline import bloom_membership_batch

        return bloom_membership_batch(
            jnp.asarray(flt.words), keys, salt,
            num_bits=flt.num_bits, num_hashes=flt.num_hashes)

    # -- sync ----------------------------------------------------------------

    def sync_once(self) -> None:
        now = time.monotonic()
        with self._lock:
            since_full = (now - self._last_full_fetch
                          if self._last_full_fetch else 0)
            since_any = now - self._last_fetch if self._last_fetch else 0
            force_full = (self._filter is None
                          or since_full >= self._full_interval)
        req = api.cache.FetchBloomFilterRequest(
            token=self._token,
            seconds_since_last_full_fetch=0 if force_full
            else int(since_full),
            seconds_since_last_fetch=0 if force_full else int(since_any),
        )
        try:
            resp, att = self._chan().call(
                "ytpu.CacheService", "FetchBloomFilter", req,
                api.cache.FetchBloomFilterResponse, timeout=10.0)
        except RpcError as e:
            logger.warning("bloom sync failed: %s", e)
            return
        with self._lock:
            self._last_fetch = now
            if resp.incremental:
                if self._filter is not None:
                    # Batched insert: one vectorized fingerprint pass
                    # over the sync window, not a digest call per key.
                    self._filter.add_many(
                        list(resp.newly_populated_keys))
            else:
                data = compress.try_decompress(att)
                if data is not None and len(data) > 4:
                    self._salt = int.from_bytes(data[:4], "little")
                    self._filter = bloom.SaltedBloomFilter.from_bytes(
                        data[4:], resp.num_hashes, self._salt)
                    self._last_full_fetch = now

    def _loop(self) -> None:
        while not self._stop.wait(timeout=_SYNC_INTERVAL_S):
            self.sync_once()

    def _chan(self) -> Channel:
        with self._lock:
            if self._channel is None:
                self._channel = Channel(self._uri)
            return self._channel

    def inspect(self) -> dict:
        with self._lock:
            return {"synced": self._filter is not None, "hits": self.hits,
                    "bloom_rejects": self.bloom_rejects,
                    "misses": self.misses}
