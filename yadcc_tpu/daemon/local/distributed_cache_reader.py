"""Delegate-side distributed cache reader with a local Bloom replica.

Parity with reference yadcc/daemon/local/distributed_cache_reader.h:32-56:
the daemon keeps a replica of the cache server's Bloom filter, synced
incrementally (new keys) with a jittered ~10-minute full refetch, and
TryRead() short-circuits guaranteed misses locally so cold builds don't
pay a network round trip per TU.

TPU path: when a batch of keys needs testing at once (burst submits,
the benchmark sweep), the replica's word array is probed on-device via
ops/bloom_probe.py — see batch_may_contain().

Cascade: against a cache server with a shared L3 tier, the reader also
replicates the FLEET filter (keys in the L3 bucket, synced via
FetchFleetBloomFilter on the same incremental/full protocol) and
batch_may_contain answers "region OR fleet" in one device-sharded
launch (parallel/mesh.py:sharded_bloom_cascade_fn) — a key the region
never served but a peer region uploaded still predicts as a hit, which
is what makes L3 read-through worth the retry.  Servers without an L3
answer NOT_FOUND once and the reader permanently falls back to the
single-filter path.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional

from ... import api
from ...common import bloom, compress
from ...rpc import Channel, RpcError
from ...rpc.transport import STATUS_METHOD_NOT_FOUND
from ...utils.logging import get_logger

logger = get_logger("daemon.cache_reader")

_FULL_FETCH_INTERVAL_S = 600.0  # ~10min, jittered per client
_SYNC_INTERVAL_S = 10.0


class DistributedCacheReader:
    def __init__(self, cache_server_uri: str, token: str,
                 use_device_cascade: bool = True):
        self._uri = cache_server_uri
        self._token = token
        self._use_device_cascade = use_device_cascade
        self._lock = threading.Lock()
        # Learned from each full fetch (rides the payload); paired with
        # _filter — they must only ever be read together under the lock
        # (a full fetch replaces both; a torn read probes the new words
        # with the old salt and returns garbage membership).
        self._salt = 0  # guarded by: self._lock
        self._filter: Optional[bloom.SaltedBloomFilter] = \
            None  # guarded by: self._lock
        self._last_full_fetch = 0.0  # guarded by: self._lock
        self._last_fetch = 0.0  # guarded by: self._lock
        # Fleet-filter replica (the cascade's L3 level): same pairing
        # rule as (_salt, _filter) above.
        self._fleet_salt = 0  # guarded by: self._lock
        self._fleet_filter: Optional[bloom.SaltedBloomFilter] = \
            None  # guarded by: self._lock
        self._fleet_last_full_fetch = 0.0  # guarded by: self._lock
        self._fleet_last_fetch = 0.0  # guarded by: self._lock
        self._fleet_unsupported = False  # guarded by: self._lock
        self._full_interval = _FULL_FETCH_INTERVAL_S * random.uniform(0.9, 1.1)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._channel: Optional[Channel] = None  # guarded by: self._lock
        self._cascade = None  # lazy DeviceBloomCascade; jit-cache holder
        self.hits = 0  # guarded by: self._lock
        self.bloom_rejects = 0  # guarded by: self._lock
        self.misses = 0  # guarded by: self._lock

    @property
    def enabled(self) -> bool:
        return bool(self._uri)

    def start(self) -> None:
        if not self.enabled:
            return
        self.sync_once()
        self._thread = threading.Thread(target=self._loop,
                                        name="bloom-sync", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    # -- reads ---------------------------------------------------------------

    def try_read(self, key: str) -> Optional[bytes]:
        """None on miss (including Bloom-filtered definite misses)."""
        if not self.enabled:
            return None
        with self._lock:
            flt = self._filter
            fleet = self._fleet_filter
        if (flt is not None and not flt.may_contain(key)
                and (fleet is None or not fleet.may_contain(key))):
            # Definite miss in every cascade level the reader knows
            # about.  A fleet-only maybe still goes to the server: the
            # entry lives in L3 and the async promote makes the *next*
            # read a hit even though this one answers NOT_FOUND.
            with self._lock:
                self.bloom_rejects += 1
            return None
        try:
            _, value = self._chan().call(
                "ytpu.CacheService", "TryGetEntry",
                api.cache.TryGetEntryRequest(token=self._token, key=key),
                api.cache.TryGetEntryResponse, timeout=5.0)
            with self._lock:
                self.hits += 1
            return value
        except RpcError:
            with self._lock:
                self.misses += 1
            return None

    def batch_may_contain(self, keys: List[str]):
        """Device-side batch Bloom test; numpy bool array (all-True when
        no filter is synced yet — absence of evidence isn't a miss).

        Rides the fused fingerprint→probe pipeline: the replica's raw
        key bytes go up once and one bool[N] comes back — no host
        hashing, no [N, 2] fingerprint upload (ops/bloom_pipeline.py).
        With a fleet replica synced, region and fleet filters resolve in
        ONE cascade launch (region-maybe OR fleet-maybe per key)."""
        import numpy as np

        # Snapshot filters AND salts under one lock hold: a concurrent
        # full fetch swaps a (words, salt) pair, and probing new words
        # with the old salt (or vice versa) yields wrong membership
        # answers — found by ytpu-analyze (guarded-by) when _salt
        # gained its annotation.
        with self._lock:
            flt = self._filter
            salt = self._salt
            fleet = self._fleet_filter
        if flt is None or not keys:
            return np.ones(len(keys), bool)
        if (fleet is not None and self._use_device_cascade
                and fleet.num_bits == flt.num_bits):
            if self._cascade is None:
                from ...cache.bloom_filter_generator import \
                    DeviceBloomCascade
                self._cascade = DeviceBloomCascade()
            return self._cascade.may_contain_batch(flt, fleet, keys)
        import jax.numpy as jnp

        from ...ops.bloom_pipeline import bloom_membership_batch

        verdict = bloom_membership_batch(
            jnp.asarray(flt.words), keys, salt,
            num_bits=flt.num_bits, num_hashes=flt.num_hashes)
        if fleet is not None:
            # Geometry mismatch (or cascade disabled): two single-filter
            # launches, host OR — same verdicts, one extra launch.
            verdict = verdict | bloom_membership_batch(
                jnp.asarray(fleet.words), keys, fleet.salt,
                num_bits=fleet.num_bits, num_hashes=fleet.num_hashes)
        return verdict

    # -- sync ----------------------------------------------------------------

    def sync_once(self) -> None:
        self._sync_filter("FetchBloomFilter")
        with self._lock:
            skip_fleet = self._fleet_unsupported
        if not skip_fleet:
            self._sync_filter("FetchFleetBloomFilter")

    def _sync_filter(self, method: str) -> None:
        """One sync round for one cascade level.  Region state and fleet
        state are disjoint (method-selected below) but follow the same
        incremental/full protocol."""
        is_fleet = method == "FetchFleetBloomFilter"
        now = time.monotonic()
        with self._lock:
            if is_fleet:
                last_full = self._fleet_last_full_fetch
                last_any = self._fleet_last_fetch
                have = self._fleet_filter is not None
            else:
                last_full = self._last_full_fetch
                last_any = self._last_fetch
                have = self._filter is not None
            since_full = now - last_full if last_full else 0
            since_any = now - last_any if last_any else 0
            force_full = not have or since_full >= self._full_interval
        req = api.cache.FetchBloomFilterRequest(
            token=self._token,
            seconds_since_last_full_fetch=0 if force_full
            else int(since_full),
            seconds_since_last_fetch=0 if force_full else int(since_any),
        )
        try:
            resp, att = self._chan().call(
                "ytpu.CacheService", method, req,
                api.cache.FetchBloomFilterResponse, timeout=10.0)
        except RpcError as e:
            if is_fleet and e.status in (api.cache.CACHE_STATUS_NOT_FOUND,
                                         STATUS_METHOD_NOT_FOUND):
                # Server has no L3 tier (or predates the RPC): stop
                # asking — the single-filter path is the whole story.
                with self._lock:
                    self._fleet_unsupported = True
                logger.info("cache server has no fleet filter; "
                            "cascade disabled")
            else:
                logger.warning("bloom sync (%s) failed: %s", method, e)
            return
        with self._lock:
            if is_fleet:
                self._fleet_last_fetch = now
            else:
                self._last_fetch = now
            if resp.incremental:
                target = self._fleet_filter if is_fleet else self._filter
                if target is not None:
                    # Batched insert: one vectorized fingerprint pass
                    # over the sync window, not a digest call per key.
                    target.add_many(list(resp.newly_populated_keys))
            else:
                data = compress.try_decompress(att)
                if data is not None and len(data) > 4:
                    salt = int.from_bytes(data[:4], "little")
                    new = bloom.SaltedBloomFilter.from_bytes(
                        data[4:], resp.num_hashes, salt)
                    if is_fleet:
                        self._fleet_salt = salt
                        self._fleet_filter = new
                        self._fleet_last_full_fetch = now
                    else:
                        self._salt = salt
                        self._filter = new
                        self._last_full_fetch = now

    def _loop(self) -> None:
        while not self._stop.wait(timeout=_SYNC_INTERVAL_S):
            self.sync_once()

    def _chan(self) -> Channel:
        with self._lock:
            if self._channel is None:
                self._channel = Channel(self._uri)
            return self._channel

    def inspect(self) -> dict:
        with self._lock:
            return {"synced": self._filter is not None, "hits": self.hits,
                    "fleet_synced": self._fleet_filter is not None,
                    "bloom_rejects": self.bloom_rejects,
                    "misses": self.misses}
