"""Grant prefetching and batching.

Parity with reference yadcc/daemon/local/task_grant_keeper.{h,cc}: one
fetcher thread per compilation environment pulls grants from the
scheduler, requesting `immediate = waiters` plus one prefetch so the
next task usually finds a grant already queued (latency hiding —
task_grant_keeper.cc:117-183).  Grants carry a 15s lease minus a 5s
network-tolerance margin; stale queue entries are freed back rather
than handed out.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from ... import api
from ...common.backoff import Backoff
from ...common.consistent_hash import (SCHEDULER_VNODES_PER_WEIGHT,
                                       ConsistentHash)
from ...rpc import Channel, RpcError
from ...utils.logging import get_logger
from .fair_admission import FairGrantQueue

logger = get_logger("daemon.grant_keeper")

_LEASE_S = 15.0
_NETWORK_TOLERANCE_S = 5.0
# How long a scheduler flow-control verdict (overload ladder,
# doc/robustness.md) stays authoritative when the scheduler attached no
# retry-after of its own.
_FLOW_DEFAULT_TTL_S = 1.0
# Long-poll lap length.  The reference issues one 5s poll per demand
# window; we split it into short laps so a fetcher observes retire()/
# stop() within one lap instead of lingering in a blocked RPC for the
# whole poll (the round-3 thread leak: retired fetchers survived ~8s
# past retirement, unbounded under compiler-env churn).  A scheduler
# with grants available answers a lap instantly, so throughput is
# unaffected; only the dry-scheduler case polls more often.
_POLL_LAP_MS = 1000
_RPC_TIMEOUT_MARGIN_S = 1.5


@dataclass
class Grant:
    grant_id: int
    servant_location: str
    usable_until: float


class _EnvFetcher:
    def __init__(self, keeper: "TaskGrantKeeper", env_digest: str,
                 tenant: str = ""):
        self.keeper = keeper
        self.env_digest = env_digest
        # Multi-tenant QoS (doc/tenancy.md): fetchers are keyed by
        # (env, tenant) so each fetch carries exactly ONE tenant's
        # credential and the scheduler's per-tenant ledger attributes
        # every minted grant to the tenant that asked — a shared
        # fetcher would launder all demand under one identity.
        self.tenant = tenant
        # Weighted-fair hand-out keyed by requestor: one make -j500
        # must not starve the other clients on this box
        # (doc/robustness.md, "Fairness quotas").
        self.queue = FairGrantQueue()
        self.waiters = 0  # guarded by: self.lock
        self.lock = threading.Lock()
        self.wake = threading.Event()
        self.retired = threading.Event()
        self.last_used = time.monotonic()
        self.thread = threading.Thread(
            target=self._loop, name=f"grant-fetch-{env_digest[:8]}",
            daemon=True)
        self.thread.start()

    def get(self, timeout_s: float, client_key: str = "",
            weight: float = 1.0, tenant: str = "",
            tenant_weight: float = 1.0) -> Optional[Grant]:
        deadline = time.monotonic() + timeout_s
        with self.lock:
            self.waiters += 1
            self.last_used = time.monotonic()
        self.wake.set()
        try:
            while True:
                if self.retired.is_set():
                    # Retired under us (idle sweep / stop): the closed
                    # queue yields nothing; the keeper hands the next
                    # call a fresh fetcher.
                    return None
                if self.keeper.local_only_active():
                    # The scheduler said compile-locally; fail FAST so
                    # the caller's local fallback starts now, not after
                    # a 10s grant wait that cannot succeed.
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                g = self.queue.get(client_key, weight,
                                   timeout_s=min(remaining, 0.5),
                                   tenant=tenant,
                                   tenant_weight=tenant_weight)
                if g is None:
                    self.wake.set()  # fetcher may have gone idle
                    continue
                if g.usable_until > time.monotonic():
                    return g
                # Expired while queued: return it to the scheduler.
                self.keeper._free_async([g.grant_id])
        finally:
            with self.lock:
                self.waiters -= 1

    def retire(self) -> None:
        """Stop the fetch thread and hand queued grants back.  Called
        with no waiters; late racers re-create a fresh fetcher.  The
        queue is CLOSED first so a fetch landing after this point
        parks its grants in the backlog (freed by the loop's exit
        drain) instead of handing them to a late waiter of a dead
        fetcher; the loop drains again on exit for exactly that case."""
        self.retired.set()
        self.wake.set()
        self.queue.close()
        self._drain_and_free()

    def _drain_and_free(self) -> None:
        stale = [g.grant_id for g in self.queue.drain()]
        if stale:
            self.keeper._free_async(stale)

    def _stopped(self) -> bool:
        return (self.keeper._stopping.is_set() or self.retired.is_set())

    def _loop(self) -> None:
        # Dry-scheduler pacing: bounded exponential backoff with full
        # jitter (common/backoff.py) instead of the old fixed 0.1s lap,
        # honoring the scheduler's retry-after when its overload ladder
        # sent one.  Sleeps ride `retired.wait` so retirement still
        # interrupts within one delay.
        backoff = Backoff(initial_s=0.05, max_s=2.0)
        while not self._stopped():
            self.wake.wait(timeout=0.5)
            self.wake.clear()
            if self._stopped():
                break
            if self.keeper.local_only_active():
                continue  # waiters are failing fast to local compiles
            with self.lock:
                waiters = self.waiters
            backlog = self.queue.qsize()
            if waiters <= backlog:
                continue  # queued grants already cover the demand
            immediate = waiters - backlog
            grants, flow, retry_after_s = self.keeper._fetch(
                self.env_digest, immediate, prefetch=1,
                tenant=self.tenant)
            now = time.monotonic()
            for gid, location in grants:
                self.queue.put(Grant(
                    gid, location,
                    usable_until=now + _LEASE_S - _NETWORK_TOLERANCE_S))
            if grants:
                self.keeper._note_flow(0, 0.0)
                backoff.reset()
                continue
            if flow:
                # Explicit overload verdict: record it (waiters on
                # COMPILE_LOCALLY bail fast; REJECT paces the retry by
                # the server's own backoff hint).
                self.keeper._note_flow(flow, retry_after_s)
                if flow == api.scheduler.FLOW_CONTROL_REJECT:
                    self.retired.wait(backoff.next_delay(retry_after_s))
                continue
            self.retired.wait(backoff.next_delay())  # scheduler dry
        if self.retired.is_set() or self.keeper._stopping.is_set():
            # A fetch that was in flight when retire() drained may have
            # enqueued grants after that drain: free them too, or the
            # scheduler holds those slots until the lease expires.
            self._drain_and_free()


class TaskGrantKeeper:
    # A fetcher for a compiler env nobody has used in this long is
    # retired (thread stopped, queued grants freed): a delegate in a
    # fleet with rotating toolchains must not accumulate one thread +
    # queue per env digest it has EVER seen.
    IDLE_FETCHER_TTL_S = 600.0

    def __init__(self, scheduler_uri: str, token: str,
                 min_version: int = 0,
                 tenant_credential_fn=None):
        # Multi-cell federation (doc/scheduler.md "Federation"):
        # ``scheduler_uri`` is ";"-separated cell groups, each group a
        # comma-separated active,standby failover list (the comma form
        # dials through rpc.FailoverChannel).  A compiler env's home
        # cell is picked by consistent hash on its digest — the same
        # ring discipline the cells use — so this delegate's fetches
        # land where that toolchain's artifacts are warm.  The common
        # single-cell "host:port" form takes the exact old path.
        self._cell_uris = [u.strip() for u in scheduler_uri.split(";")
                           if u.strip()]
        if not self._cell_uris:
            raise ValueError("scheduler_uri must name at least one cell")
        self._ring = (ConsistentHash(
            [(str(i), 1) for i in range(len(self._cell_uris))],
            vnodes_per_weight=SCHEDULER_VNODES_PER_WEIGHT)
            if len(self._cell_uris) > 1 else None)
        self._token = token
        self._min_version = min_version
        # tenant_id -> credential minting callable (typically
        # TenancyControl.credential_for).  None on untenanted
        # deployments; fetches then never set tenant_credential and the
        # wire stays byte-identical to the legacy form.
        self._tenant_credential_fn = tenant_credential_fn
        self._lock = threading.Lock()
        self._fetchers: Dict[str, _EnvFetcher] = {}  # guarded by: self._lock
        self._stopping = threading.Event()
        self._channels: Dict[int, Channel] = {}  # guarded by: self._lock
        # Last scheduler flow-control verdict and when it stops being
        # authoritative: (FlowControlVerdict value, monotonic deadline).
        self._flow: Tuple[int, float] = (0, 0.0)  # guarded by: self._lock

    def get(self, env_digest: str, timeout_s: float = 10.0,
            client_key: str = "", weight: float = 1.0,
            tenant: str = "", tenant_weight: float = 1.0
            ) -> Optional[Grant]:
        """One grant for ``env_digest``, or None.  ``client_key``
        identifies the requestor for weighted-fair hand-out (empty =
        shared anonymous client); ``tenant`` selects the outer stride
        level of the two-level queue (doc/tenancy.md; empty = shared
        legacy tenant).  Under an active compile-locally verdict this
        returns None immediately so the caller's local fallback starts
        now."""
        if self.local_only_active():
            return None
        now = time.monotonic()
        # Fetchers are keyed (env, tenant) so each carries one tenant's
        # credential; "\x00" cannot appear in a hex digest, so the
        # legacy tenant-less key space is untouched.
        fkey = env_digest if not tenant else f"{env_digest}\x00{tenant}"
        retire = []
        with self._lock:
            for key, f in list(self._fetchers.items()):
                if (key != fkey and f.waiters == 0
                        and now - f.last_used > self.IDLE_FETCHER_TTL_S):
                    retire.append(self._fetchers.pop(key))
            f = self._fetchers.get(fkey)
            if f is None or f.retired.is_set():
                f = _EnvFetcher(self, env_digest, tenant=tenant)
                self._fetchers[fkey] = f
            # Refresh under the keeper lock: the idle scan above runs
            # under the same lock, so a fetcher handed out here can
            # never be judged stale before its waiter registers.
            f.last_used = now
        for r in retire:
            r.retire()
        return f.get(timeout_s, client_key=client_key, weight=weight,
                     tenant=tenant, tenant_weight=tenant_weight)

    # -- flow-control verdict state (overload ladder) ------------------------

    def _note_flow(self, flow: int, retry_after_s: float) -> None:
        with self._lock:
            if flow == 0:
                self._flow = (0, 0.0)
            else:
                ttl = (retry_after_s if retry_after_s and retry_after_s > 0
                       else _FLOW_DEFAULT_TTL_S)
                self._flow = (flow, time.monotonic() + ttl)

    def flow_state(self) -> Tuple[int, float]:
        """(FlowControlVerdict value, seconds it stays authoritative);
        (0, 0) when the last fetch saw a healthy scheduler."""
        with self._lock:
            flow, until = self._flow
        remaining = until - time.monotonic()
        return (flow, max(0.0, remaining)) if remaining > 0 else (0, 0.0)

    def local_only_active(self) -> bool:
        flow, _ = self.flow_state()
        return flow == api.scheduler.FLOW_CONTROL_COMPILE_LOCALLY

    def free(self, grant_ids) -> None:
        self._free_async(list(grant_ids))

    def keep_alive(self, grant_ids) -> list:
        """Renew leases in batch; returns per-grant success."""
        try:
            resp, _ = self._chan().call(
                "ytpu.SchedulerService", "KeepTaskAlive",
                api.scheduler.KeepTaskAliveRequest(
                    token=self._token,
                    task_grant_ids=list(grant_ids),
                    next_keep_alive_in_ms=int(_LEASE_S * 1000)),
                api.scheduler.KeepTaskAliveResponse, timeout=5.0)
            return list(resp.statuses)
        except RpcError as e:
            logger.warning("KeepTaskAlive failed: %s", e)
            return [False] * len(list(grant_ids))

    def stop(self, join_timeout_s: float = 5.0) -> None:
        """Stop all fetchers and wait (bounded) for their threads to
        exit.  Joining matters: a fetcher blocked in its long-poll lap
        exits within ~one lap, and callers (daemon shutdown, tests)
        must not strand live `grant-fetch-*` threads behind them."""
        self._stopping.set()
        with self._lock:
            fetchers = list(self._fetchers.values())
            self._fetchers.clear()
        for f in fetchers:
            f.retired.set()
            f.wake.set()
            f.queue.close()
        deadline = time.monotonic() + join_timeout_s
        for f in fetchers:
            f.thread.join(timeout=max(0.0, deadline - time.monotonic()))
        for f in fetchers:
            f._drain_and_free()

    # -- internals -----------------------------------------------------------

    def _chan(self, env_digest: str = "") -> Channel:
        """Channel to the env's home cell (empty digest = cell 0).
        Renew/free carry only grant ids, not digests; they go to cell 0
        and the federation router routes them home by the grant-id
        namespace — any cell can accept them."""
        cell = (int(self._ring.pick(env_digest))
                if self._ring is not None and env_digest else 0)
        with self._lock:
            ch = self._channels.get(cell)
            if ch is None:
                ch = self._channels[cell] = Channel(self._cell_uris[cell])
            return ch

    def _fetch(self, env_digest: str, immediate: int, prefetch: int,
               tenant: str = ""):
        """One grant poll.  Returns (grants, flow_verdict,
        retry_after_s): flow_verdict is the scheduler's overload-ladder
        answer (FlowControlVerdict value, 0 = none) and retry_after_s
        its server-computed backoff hint."""
        req = api.scheduler.WaitForStartingTaskRequest(
            token=self._token,
            milliseconds_to_wait=_POLL_LAP_MS,
            immediate_reqs=immediate,
            prefetch_reqs=prefetch,
            next_keep_alive_in_ms=int(_LEASE_S * 1000),
            min_version=self._min_version,
        )
        req.env_desc.compiler_digest = env_digest
        if tenant and self._tenant_credential_fn is not None:
            try:
                req.tenant_credential = self._tenant_credential_fn(tenant)
            except Exception:
                # No mintable window token right now: send no
                # credential and let the scheduler fail closed rather
                # than killing the fetch loop.
                logger.warning("could not mint credential for tenant %r",
                               tenant)
        try:
            resp, _ = self._chan(env_digest).call(
                "ytpu.SchedulerService", "WaitForStartingTask", req,
                api.scheduler.WaitForStartingTaskResponse,
                timeout=_POLL_LAP_MS / 1000.0 + _RPC_TIMEOUT_MARGIN_S)
            return ([(g.task_grant_id, g.servant_location)
                     for g in resp.grants],
                    resp.flow_control, resp.retry_after_ms / 1000.0)
        except RpcError:
            return [], 0, 0.0

    def _free_async(self, grant_ids) -> None:
        if not grant_ids:
            return

        def run():
            try:
                self._chan().call(
                    "ytpu.SchedulerService", "FreeTask",
                    api.scheduler.FreeTaskRequest(
                        token=self._token, task_grant_ids=grant_ids),
                    api.scheduler.FreeTaskResponse, timeout=5.0)
            except RpcError:
                pass  # lease expiry will reclaim it

        threading.Thread(target=run, name="grant-free", daemon=True).start()
