"""Delegate-side task dispatcher: one state machine per in-flight task.

Workload-agnostic: everything task-specific (cache key, dedup digest,
servant submission, output parsing) lives behind the DistributedTask
SPI, and the servant's wait/reference/free RPC surface is shared by all
task kinds — so C++ TUs and XLA jit compilations run through this same
machine, interleaved, with per-kind provenance counters.

Parity with reference yadcc/daemon/local/distributed_task_dispatcher
.{h,cc}: a queued task runs Pending -> ReadyToFire -> Dispatched -> Done
(:146-158), trying in order (1) the distributed cache, (2) joining an
identical task already running somewhere in the cluster, (3) acquiring
a grant and dispatching to the chosen servant (:197-234), then long-
polling the servant with a retry budget (:365-421).  Four 1s timers keep
the world consistent: abort deadline, batched scheduler keep-alives,
orphan kill (submitter PID died), completed-task GC (:550-706).

The reference runs one fiber per task; here it's one thread per task —
the daemon's in-flight TU count is bounded by the client-side quota
(LocalTaskMonitor), so thread counts stay in the tens.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Optional

from ... import api
from ...jit import fanout
from ...rpc import Channel, RpcError
from ...tenancy import TenantLedger, TenantOverBudget
from ...utils.logging import get_logger
from .config_keeper import ConfigKeeper
from .distributed_cache_reader import DistributedCacheReader
from .distributed_task import DistributedTask, TaskResult
from .running_task_keeper import RunningTaskKeeper
from .task_grant_keeper import TaskGrantKeeper

logger = get_logger("daemon.task_dispatcher")

_LONG_POLL_MS = 2000
_LONG_POLL_RETRIES = 4
_ABORT_AFTER_S = 300.0     # hard ceiling per TU
_COMPLETED_RETENTION_S = 60.0
_KEEP_ALIVE_BATCH_S = 10.0


class TaskState(Enum):
    PENDING = "pending"
    DISPATCHED = "dispatched"
    DONE = "done"


@dataclass
class _Entry:
    task_id: int
    task: DistributedTask
    state: TaskState = TaskState.PENDING
    started_at: float = field(default_factory=time.monotonic)
    completed_at: Optional[float] = None
    grant_id: Optional[int] = None
    servant_location: Optional[str] = None
    servant_task_id: Optional[int] = None
    result: Optional[TaskResult] = None
    done: threading.Event = field(default_factory=threading.Event)
    aborted: bool = False
    # Parked long-poll continuations (aio front end): fired once with
    # the TaskResult when the task completes; a waiting client costs
    # this list entry, not a serving thread.
    waiters: list = field(default_factory=list)


class DistributedTaskDispatcher:
    def __init__(
        self,
        *,
        grant_keeper: TaskGrantKeeper,
        config_keeper: ConfigKeeper,
        cache_reader: Optional[DistributedCacheReader] = None,
        running_task_keeper: Optional[RunningTaskKeeper] = None,
        pid_prober=None,
        debugging_always_use_servant_at: str = "",
        cache_writer=None,
        # Transport scheme for dialing peer servants (their registry
        # locations are bare host:port).  "aio://" when the fleet runs
        # the event-loop front end (--rpc-frontend aio).
        servant_scheme: str = "grpc://",
        # Delegate-side per-tenant budget ledger (doc/tenancy.md): an
        # over-budget tenant's submission is refused AT THE DOOR
        # (queue_task raises TenantOverBudget -> HTTP 503 +
        # Retry-After) instead of occupying a task thread.  None =
        # unbudgeted (single-tenant deployments).
        tenant_ledger: Optional[TenantLedger] = None,
    ):
        self._grants = grant_keeper
        self._config = config_keeper
        self._cache = cache_reader
        self._running = running_task_keeper
        # Delegate-side cache fills — used ONLY by fan-out parents
        # whose reduced verdict is itself cacheable (the autotune
        # sweep-level winner record); per-child artifacts still fill
        # servant-side like every other workload.  None = no parent
        # fills (the parent result is still correct, just not shared).
        self._cache_writer = cache_writer
        self._pid_alive = pid_prober or _default_pid_alive
        # Debug override (reference --debugging_always_use_servant_at):
        # every servant dial goes HERE; grants still flow normally.
        self._debug_servant = debugging_always_use_servant_at
        self._servant_scheme = servant_scheme
        self._tenant_ledger = tenant_ledger
        self._lock = threading.Lock()
        self._tasks: Dict[int, _Entry] = {}  # guarded by: self._lock
        self._next_id = 1  # guarded by: self._lock
        self._channels: Dict[str, Channel] = {}  # guarded by: self._lock
        self.stats = {"hit_cache": 0, "reused": 0, "actually_run": 0,
                      "failed": 0,
                      "shed_to_local": 0}  # guarded by: self._lock
        # Same counters split per task kind ("cxx"/"jit"/...): the
        # aggregate above is the long-standing public surface, the
        # split is what a mixed-workload deployment actually watches.
        self.stats_by_kind: Dict[str, Dict[str, int]] = {}  # guarded by: self._lock
        # And split per tenant ("" entries are never created): the
        # noisy-neighbor scenario reads victim/adversary provenance
        # from here.
        self.stats_by_tenant: Dict[str, Dict[str, int]] = {}  # guarded by: self._lock

    # -- public API ----------------------------------------------------------

    def stop(self) -> None:
        """Ordered shutdown: stop the grant keeper (joins its fetcher
        threads — without this every keeper leaks one `grant-fetch-*`
        thread per compiler env for the process lifetime) and the
        cache reader's refresh loop.  In-flight task threads are
        daemonic and finish or die with the process."""
        self._grants.stop()
        if self._cache is not None and hasattr(self._cache, "stop"):
            self._cache.stop()

    def queue_task(self, task: DistributedTask) -> int:
        tenant = task.fairness_tenant()
        if self._tenant_ledger is not None and tenant:
            # Budget check at the door: an over-budget tenant is
            # refused before a task thread or queue slot exists, so
            # its refused demand is invisible to everyone else.
            if self._tenant_ledger.over_budget(tenant, want_immediate=1):
                raise TenantOverBudget(tenant)
            self._tenant_ledger.charge(tenant)
        with self._lock:
            entry = _Entry(task_id=self._next_id, task=task)
            self._next_id += 1
            self._tasks[entry.task_id] = entry
        threading.Thread(
            target=self._perform_one_task, args=(entry,),
            name=f"{task.kind}-{entry.task_id}", daemon=True,
        ).start()
        return entry.task_id

    def _bump_locked(self, kind: str, counter: str,
                     tenant: str = "") -> None:
        """Increment a provenance counter; caller holds self._lock."""
        self.stats[counter] += 1
        per = self.stats_by_kind.setdefault(
            kind, {k: 0 for k in self.stats})
        per[counter] += 1
        if tenant:
            pt = self.stats_by_tenant.setdefault(
                tenant, {k: 0 for k in self.stats})
            pt[counter] += 1

    def wait_for_task(self, task_id: int,
                      timeout_s: float) -> Optional[TaskResult]:
        with self._lock:
            entry = self._tasks.get(task_id)
        if entry is None:
            return None
        entry.done.wait(timeout=timeout_s)
        return entry.result

    def wait_for_task_async(self, task_id: int, on_done) -> bool:  # ytpu: responder(on_done)  # ytpu: allow(reply-drop)  # unknown id: the False return hands the reply back to the caller, which answers 404
        """Parked-continuation twin of wait_for_task (aio front end):
        ``on_done(result)`` fires from the completing task thread, or
        immediately when the task already finished.  Returns False for
        an unknown task id (the caller answers 404 — same contract as
        wait_for_task returning None on unknown).  The caller owns the
        long-poll deadline: its loop timer answers 503 and the late
        completion callback becomes a no-op (reply-once responder)."""
        with self._lock:
            entry = self._tasks.get(task_id)
            if entry is None:
                return False
            if entry.state != TaskState.DONE:
                entry.waiters.append(on_done)
                return True
            result = entry.result
        on_done(result)
        return True

    def free_task(self, task_id: int) -> None:
        with self._lock:
            self._tasks.pop(task_id, None)

    def is_known(self, task_id: int) -> bool:
        with self._lock:
            return task_id in self._tasks

    # -- the per-TU state machine -------------------------------------------

    def _perform_one_task(self, entry: _Entry) -> None:
        try:
            result = self._try_read_cache(entry)
            if result is None and entry.task.is_fanout:
                result = self._perform_fanout(entry)
            if result is None:
                result = self._try_join_existing(entry)
            if result is None:
                result = self._start_new_servant_task(entry)
        except Exception as e:  # never leave a waiter hanging
            logger.exception("task %d failed", entry.task_id)
            result = TaskResult(
                exit_code=-1,
                standard_error=f"ytpu daemon error: {e!r}".encode())
            # Counter updates take the lock: one TU thread runs per
            # in-flight task, and dict `+=` is a read-modify-write that
            # loses increments when two of them interleave.
            # The failure path must not raise: the task object that just
            # blew up may not implement the full SPI, and an exception
            # here would leave the waiter hanging after all.
            tenant = getattr(entry.task, "fairness_tenant", lambda: "")()
            with self._lock:
                self._bump_locked(entry.task.kind, "failed", tenant)
        if self._tenant_ledger is not None:
            # Every exit path lands here (the try/except above never
            # re-raises), so the outstanding count is exact.
            self._tenant_ledger.release(
                getattr(entry.task, "fairness_tenant", lambda: "")())
        with self._lock:
            entry.result = result
            entry.state = TaskState.DONE
            entry.completed_at = time.monotonic()
            waiters, entry.waiters = entry.waiters, []
        entry.done.set()
        for cb in waiters:  # parked long-polls (aio front end)
            try:
                cb(result)
            except Exception:
                logger.exception("parked wait continuation failed")

    def _try_read_cache(self, entry: _Entry) -> Optional[TaskResult]:
        if self._cache is None or not self._cache.enabled:
            return None
        # Only CACHE_ALLOW reads; REFILL (the reference's cache-cold
        # benchmark mode, YADCC_CACHE_CONTROL=2) skips the lookup but
        # still fills on completion (reference distributed_task.h:36,
        # distributed_task_dispatcher.cc:237).
        if entry.task.get_cache_setting() != entry.task.CACHE_ALLOW:
            return None
        key = entry.task.get_cache_key()
        if key is None:
            return None
        data = self._cache.try_read(key)
        if data is None:
            return None
        result = entry.task.parse_cache_entry(data)
        if result is None:
            logger.warning("corrupted cache entry for %s", key)
            return None
        with self._lock:
            self._bump_locked(entry.task.kind, "hit_cache",
                              entry.task.fairness_tenant())
        return result

    def _perform_fanout(self, entry: _Entry) -> TaskResult:
        """Fan-out parents (jit/fanout.py): expand into child tasks —
        each a normal DistributedTask re-entering this dispatcher's
        cache→join→dispatch machinery with its own cache key, digest
        and grant — then join them with bounded retries and reduce to
        one result with explicit per-child verdicts.  The parent
        itself never talks to a servant, so it holds no grant and
        consumes no engine slot; only its children do.  Provenance:
        children bump the per-kind counters through the normal path
        (that is what makes partial hits provable via
        ``actually_run``); the parent bumps nothing on success."""
        children = entry.task.expand_children()
        if entry.task.tenant_fanout_cap:
            # Tier fan-out cap (doc/tenancy.md): a best_effort tenant's
            # sweep may not expand wider than its tier allows, however
            # generous the global YTPU_FANOUT_MAX_WIDTH bound is.
            fanout.checked_fanout_width(
                len(children), cap=entry.task.tenant_fanout_cap)
        outcomes = fanout.run_fanout(
            children,
            queue=self.queue_task,
            wait=self.wait_for_task,
            free=self.free_task,
            aborted=lambda: entry.aborted,
        )
        result = entry.task.reduce(outcomes)
        self._maybe_fill_parent_cache(entry.task, result)
        return result

    def _maybe_fill_parent_cache(self, task: DistributedTask,
                                 result: TaskResult) -> None:
        if self._cache_writer is None:
            return
        make = getattr(task, "make_parent_cache_entry", None)
        if make is None:
            return
        filled = make(result)
        if filled is None:
            return
        key, payload = filled
        self._cache_writer.async_write(key, payload)

    def _try_join_existing(self, entry: _Entry) -> Optional[TaskResult]:
        """Duplicate-compilation joining (reference :256-300): if some
        servant is already compiling this digest, reference it and wait
        for ITS output instead of burning another grant."""
        if self._running is None:
            return None
        found = self._running.try_find_task(entry.task.get_digest())
        if found is None:
            return None
        token = self._config.serving_daemon_token()
        ch = self._channel(found.servant_location)
        try:
            ch.call("ytpu.DaemonService", "ReferenceTask",
                    api.daemon.ReferenceTaskRequest(
                        token=token, task_id=found.servant_task_id),
                    api.daemon.ReferenceTaskResponse, timeout=5.0)
        except RpcError:
            return None  # task finished or servant gone: fall through
        with self._lock:
            entry.state = TaskState.DISPATCHED
            entry.servant_location = found.servant_location
            entry.servant_task_id = found.servant_task_id
        result = self._wait_servant(entry, token)
        # Release the reference we took, or the joined task's refcount
        # never reaches zero and it leaks until servant GC.
        self._free_servant_task(entry, token)
        if result is not None:
            # Mark the provenance on the result too (not just the
            # counter): fan-out verdicts report "joined" from it.
            result.reused_existing = True
            with self._lock:
                self._bump_locked(entry.task.kind, "reused",
                                  entry.task.fairness_tenant())
        return result

    def _start_new_servant_task(self, entry: _Entry) -> TaskResult:
        grant = self._grants.get(entry.task.get_env_digest(), timeout_s=10.0,
                                 client_key=entry.task.fairness_key(),
                                 weight=entry.task.fairness_weight,
                                 tenant=entry.task.fairness_tenant(),
                                 tenant_weight=entry.task.tenant_weight)
        if grant is None:
            if self._grants.local_only_active():
                # Explicit overload-ladder verdict, not a timeout: the
                # scheduler told this box to use its own CPU.  Count it
                # so a fleet shedding load is visible in /inspect.
                with self._lock:
                    self._bump_locked(entry.task.kind, "shed_to_local",
                                      entry.task.fairness_tenant())
                return TaskResult(
                    exit_code=-1,
                    standard_error=b"cluster overloaded (LOCAL_ONLY "
                                   b"verdict): compile locally")
            return TaskResult(
                exit_code=-1,
                standard_error=b"no compile capacity available in cluster")
        token = self._config.serving_daemon_token()
        ch = self._channel(grant.servant_location)
        try:
            servant_task_id = entry.task.start_task(ch, token,
                                                    grant.grant_id)
        except RpcError as e:
            self._grants.free([grant.grant_id])
            return TaskResult(
                exit_code=-1,
                standard_error=f"servant rejected task: {e}".encode())
        with self._lock:
            entry.state = TaskState.DISPATCHED
            entry.grant_id = grant.grant_id
            entry.servant_location = grant.servant_location
            entry.servant_task_id = servant_task_id
        result = self._wait_servant(entry, token)
        self._free_servant_task(entry, token)
        self._grants.free([grant.grant_id])
        if result is None:
            result = TaskResult(
                exit_code=-1,
                standard_error=b"servant lost while compiling")
        else:
            with self._lock:
                self._bump_locked(entry.task.kind, "actually_run",
                                  entry.task.fairness_tenant())
        return result

    def _wait_servant(self, entry: _Entry,
                      token: str) -> Optional[TaskResult]:
        ch = self._channel(entry.servant_location)
        retries = 0
        while retries <= _LONG_POLL_RETRIES:
            if entry.aborted:
                return None
            req = api.daemon.WaitForCompilationOutputRequest(
                token=token,
                task_id=entry.servant_task_id,
                milliseconds_to_wait=_LONG_POLL_MS,
            )
            req.acceptable_compression_algorithms.append(
                api.daemon.COMPRESSION_ALGORITHM_ZSTD)
            try:
                resp, att = ch.call(
                    "ytpu.DaemonService", "WaitForCompilationOutput", req,
                    api.daemon.WaitForCompilationOutputResponse,
                    timeout=_LONG_POLL_MS / 1000.0 + 5.0)
            except RpcError:
                retries += 1
                continue
            if resp.status == api.daemon.COMPILATION_TASK_STATUS_RUNNING:
                continue  # still compiling: poll again, no retry charge
            if resp.status == api.daemon.COMPILATION_TASK_STATUS_DONE:
                return entry.task.parse_servant_output(resp, att)
            return None  # NOT_FOUND / FAILED
        return None

    def _free_servant_task(self, entry: _Entry, token: str) -> None:
        if entry.servant_task_id is None:
            return
        ch = self._channel(entry.servant_location)
        try:
            ch.call("ytpu.DaemonService", "FreeTask",
                    api.daemon.FreeDaemonTaskRequest(
                        token=token, task_id=entry.servant_task_id),
                    api.daemon.FreeDaemonTaskResponse, timeout=5.0)
        except RpcError:
            pass  # servant GC will reclaim

    # -- timers (call each ~1s from the daemon's timer thread) ---------------

    def on_timer(self) -> None:
        now = time.monotonic()
        keep_alive_ids = []
        with self._lock:
            for entry in list(self._tasks.values()):
                if entry.state == TaskState.DONE:
                    if (entry.completed_at is not None
                            and now - entry.completed_at
                            > _COMPLETED_RETENTION_S):
                        del self._tasks[entry.task_id]
                    continue
                if now - entry.started_at > _ABORT_AFTER_S:
                    entry.aborted = True
                    continue
                if not self._pid_alive(entry.task.requestor_pid):
                    # Orphan: the submitting client died.
                    entry.aborted = True
                    continue
                if entry.grant_id is not None:
                    keep_alive_ids.append(entry.grant_id)
        if keep_alive_ids and (now - getattr(self, "_last_ka", 0)
                               >= _KEEP_ALIVE_BATCH_S):
            self._last_ka = now
            self._grants.keep_alive(keep_alive_ids)

    # -- plumbing ------------------------------------------------------------

    def _channel(self, location: str) -> Channel:
        if self._debug_servant:
            location = self._debug_servant
        with self._lock:
            ch = self._channels.get(location)
            if ch is None:
                scheme = "" if "://" in location else self._servant_scheme
                ch = Channel(scheme + location)
                self._channels[location] = ch
            return ch

    def inspect(self) -> dict:
        with self._lock:
            out = {
                "in_flight": sum(1 for e in self._tasks.values()
                                 if e.state != TaskState.DONE),
                "retained": sum(1 for e in self._tasks.values()
                                if e.state == TaskState.DONE),
                "stats": dict(self.stats),
                "stats_by_kind": {k: dict(v) for k, v
                                  in self.stats_by_kind.items()},
                "stats_by_tenant": {k: dict(v) for k, v
                                    in self.stats_by_tenant.items()},
            }
        if self._tenant_ledger is not None:
            out["tenant_budgets"] = self._tenant_ledger.inspect()
        return out


def _default_pid_alive(pid: int) -> bool:
    from .local_task_monitor import _pid_alive

    if pid <= 0:
        return True  # unknown submitter: never orphan-kill
    return _pid_alive(pid)
