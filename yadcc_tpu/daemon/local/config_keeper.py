"""Periodic scheduler-config puller.

Parity with reference yadcc/daemon/local/config_keeper.h:28-48: the
delegate needs the rotating serving-daemon token (to talk to servants
and to the cache server's Put gate is servant-side; here it's the
delegate->servant credential) — pulled via GetConfig every few seconds.
"""

from __future__ import annotations

import threading
from typing import Optional

from ... import api
from ...rpc import Channel, RpcError
from ...utils.logging import get_logger

logger = get_logger("daemon.config_keeper")


class ConfigKeeper:
    def __init__(self, scheduler_uri: str, token: str,
                 refresh_interval_s: float = 10.0):
        self._uri = scheduler_uri
        self._token = token
        self._interval = refresh_interval_s
        self._lock = threading.Lock()
        self._serving_daemon_token = ""  # guarded by: self._lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._channel: Optional[Channel] = None  # guarded by: self._lock

    def start(self) -> None:
        self.refresh_once()
        self._thread = threading.Thread(target=self._loop,
                                        name="config-keeper", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def serving_daemon_token(self) -> str:
        with self._lock:
            return self._serving_daemon_token

    def refresh_once(self) -> None:
        try:
            resp, _ = self._chan().call(
                "ytpu.SchedulerService", "GetConfig",
                api.scheduler.GetConfigRequest(token=self._token),
                api.scheduler.GetConfigResponse, timeout=5.0)
            with self._lock:
                self._serving_daemon_token = resp.serving_daemon_token
        except RpcError as e:
            logger.warning("GetConfig failed: %s", e)

    def _chan(self) -> Channel:
        # start() calls refresh_once from the constructor thread before
        # the refresh loop exists, so channel creation must be locked
        # like every other _channel access.
        with self._lock:
            if self._channel is None:
                self._channel = Channel(self._uri)
            return self._channel

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self._interval):
            self.refresh_once()
