"""Client-facing loopback HTTP API.

Parity with reference yadcc/daemon/local/http_service_impl.{h,cc} and
the wire format in yadcc/daemon/local/README.md: plain HTTP/1.1 on
127.0.0.1, JSON message bodies (uint64 as strings, per proto3 JSON),
multi-chunk framing when attachments are present.  Routes:

    GET  /local/get_version
    POST /local/ask_to_leave
    POST /local/acquire_quota        (200 granted / 503 timeout)
    POST /local/release_quota
    POST /local/set_file_digest
    POST /local/jit_cache_get        (persistent-compile-cache shim;
                                      404: miss)
    POST /local/jit_cache_put

plus one submit/wait route PAIR per registered task kind
(task_registry.py — cxx and jit today):

    POST /local/submit_<kind>_task   (multi-chunk: json + attachment;
                                      400: fix the submission and retry
                                      — e.g. report compiler digest /
                                      jit environment first)
    POST /local/wait_for_<kind>_task (503: still running, retry;
                                      404: unknown task id)
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from google.protobuf import json_format

from ... import api
from ...common import multi_chunk
from ...common.hashing import digest_keyed
from ...common.limits import BodyTooLarge, checked_content_length, clamp_wait_s
from ...common.payload import Payload
from ...tenancy.budgets import TenantOverBudget
from ...tenancy.keys import tenant_scoped_key
from ...tenancy.tiers import tier_fanout_cap
from ...utils.logging import get_logger
from ...version import BUILT_AT, VERSION_FOR_UPGRADE
from .distributed_task_dispatcher import DistributedTaskDispatcher
from .file_digest_cache import FileDigestCache
from .local_task_monitor import LocalTaskMonitor
from .task_registry import TaskTypeRegistry, default_registry

logger = get_logger("daemon.http")

# Shim keys are opaque client-side strings (jax's own cache hashes);
# domain-hash them into a versioned namespace so they can never collide
# with task-derived cache keys.
_SHIM_KEY_PREFIX = "ytpu-jitext1-"
_SHIM_KEY_DOMAIN = "ytpu-jit-extcache"


def shim_cache_key(client_key: str,
                   tenant_secret: str = "") -> str:  # ytpu: sanitizes(key-domain, tenant-domain)
    return tenant_scoped_key(
        tenant_secret,
        _SHIM_KEY_PREFIX + digest_keyed(_SHIM_KEY_DOMAIN,
                                        client_key.encode()))


# Sentinel distinct from None: None = tenancy disabled (anonymous OK),
# _TENANT_DENIED = tenancy enabled and this request failed verification.
_TENANT_DENIED = object()
_TENANT_HEADER = "x-ytpu-tenant"
_DENIED_BODY = b'{"error":"valid tenant credential required"}'

_DENIED_BUDGET_BODY = b'{"error":"tenant over budget"}'


def _to_json(msg) -> bytes:
    # Zero-valued fields (e.g. exit_code 0) must appear explicitly: the
    # zero-dependency client reads them without proto schema knowledge.
    return json_format.MessageToJson(
        msg, preserving_proto_field_name=True,
        always_print_fields_with_no_presence=True).encode()


def _from_json(cls, data: bytes):
    msg = cls()
    json_format.Parse(data.decode(), msg, ignore_unknown_fields=True)
    return msg


class LocalHttpService:
    def __init__(
        self,
        *,
        monitor: LocalTaskMonitor,
        digest_cache: FileDigestCache,
        dispatcher: DistributedTaskDispatcher,
        on_leave: Optional[Callable[[], None]] = None,
        port: int = 8334,
        host: str = "127.0.0.1",
        registry: Optional[TaskTypeRegistry] = None,
        # Shim routes: reads go through the delegate's Bloom-replicated
        # reader, puts through the servant role's cache writer (the one
        # process runs both roles — daemon/entry.py).  Either absent =>
        # the corresponding route answers 404.
        cache_reader=None,
        cache_writer=None,
        # "threaded" = the long-standing ThreadingHTTPServer (kept
        # verbatim as the A/B + fallback); "aio" = the event-loop front
        # end (rpc/aio_server.py): long-polls (acquire_quota,
        # wait_for_*) park as continuations + a loop timer instead of a
        # serving thread each (doc/daemon.md "RPC front end").
        frontend: str = "threaded",
        # Multi-tenant QoS (doc/tenancy.md): a tenancy.TenancyControl
        # makes every POST route fail-closed on the X-Ytpu-Tenant
        # credential; the verified binding is stamped onto tasks (tier
        # fan-out caps, tenant-weighted fairness, tenant cache domain).
        # None (default) = single-tenant mode, behavior unchanged.
        tenancy=None,
    ):
        self.monitor = monitor
        self.digest_cache = digest_cache
        self.dispatcher = dispatcher
        self.on_leave = on_leave or (lambda: None)
        self.registry = registry or default_registry(digest_cache)
        self.cache_reader = cache_reader
        self.cache_writer = cache_writer
        self.frontend = frontend
        self.tenancy = tenancy
        service = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _reply(self, code: int, body=b"",
                       content_type: str = "application/json",
                       retry_after_s: Optional[float] = None):
                # `body` may be a chunked Payload: gather-write its
                # segments (wfile buffers small ones; a multi-MB object
                # file goes straight from the servant-reply buffer to
                # the socket, never joined).
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                if retry_after_s is not None:
                    # Backpressure pacing hint; clients feed it to
                    # common.backoff.Backoff instead of guessing.
                    self.send_header("Retry-After", f"{retry_after_s:g}")
                self.end_headers()
                if isinstance(body, Payload):
                    for seg in body.iter_segments():
                        self.wfile.write(seg)
                elif body:
                    self.wfile.write(body)

            def do_GET(self):
                if self.path == "/local/get_version":
                    resp = api.local.GetVersionResponse(
                        built_at=BUILT_AT,
                        version_for_upgrade=VERSION_FOR_UPGRADE)
                    self._reply(200, _to_json(resp))
                else:
                    self._reply(404)

            def do_POST(self):  # ytpu: untrusted(self.headers, self.rfile)
                # Cap BEFORE buffering: any local process can open this
                # socket, and a claimed Content-Length of terabytes
                # must be refused at the header, not handed to the
                # allocator.  413 mirrors the cap the servants enforce
                # on the decompression side.
                try:
                    length = checked_content_length(
                        self.headers.get("Content-Length", 0))
                except BodyTooLarge:
                    self._reply(413, b'{"error":"body exceeds wire cap"}')
                    return
                body = self.rfile.read(length) if length else b""
                try:
                    service._route_post(self, self.path, body)
                except Exception:
                    logger.exception("error handling %s", self.path)
                    try:
                        self._reply(500)
                    except Exception:
                        pass

        if frontend == "aio":
            from ...rpc.aio_server import AioHttpServer

            self._httpd = None
            self._aio = AioHttpServer(
                self._handle_aio, address=f"{host}:{port}",
                too_large_body=b'{"error":"body exceeds wire cap"}')
            self.port = self._aio.port
        else:
            self._aio = None
            self._httpd = ThreadingHTTPServer((host, port), Handler)
            self.port = self._httpd.server_port
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._aio is not None:
            return  # the event loop serves from construction
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="local-http", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._aio is not None:
            self._aio.stop()
            return
        self._httpd.shutdown()
        self._httpd.server_close()

    def inspect(self) -> dict:
        out = ({"frontend": "threaded", "port": self.port}
               if self._aio is None else self._aio.inspect())
        if self.tenancy is not None:
            out["tenancy"] = self.tenancy.inspect()
        return out

    # -- tenant verification (both front ends) -------------------------------

    def _tenant_binding(self, headers):
        """Resolve the request's tenant from its headers: a
        TenantBinding, None (tenancy disabled — anonymous requests keep
        their legacy behavior), or _TENANT_DENIED (tenancy enabled,
        credential missing/invalid/unknown — the caller must 403).
        Fail-closed: with tenancy on, there is no anonymous path to any
        POST route.  Takes the header mapping, not the responder: this
        helper only reads, it never replies."""
        if self.tenancy is None:
            return None
        # Works on both header shapes: the threaded front end's
        # case-insensitive Message and the aio dict (lower-cased keys).
        cred = headers.get(_TENANT_HEADER, "") if headers else ""
        binding = self.tenancy.authenticate(cred)
        return binding if binding is not None else _TENANT_DENIED

    # -- aio front end (event-loop routing) ----------------------------------

    # ytpu: loop-only
    def _handle_aio(self, responder) -> None:  # ytpu: responder(responder)
        """Runs ON the loop for every request: long-polls park, quick
        routes run inline, everything that may touch disk or RPC (cache
        shim reads, task submission) goes to the bounded worker pool.
        Route semantics and reply bodies match the threaded front end
        byte for byte (tools/rpc_frontend_bench.py --parity-smoke)."""
        if responder.method == "GET":
            if responder.path == "/local/get_version":
                responder._reply(200, _to_json(api.local.GetVersionResponse(
                    built_at=BUILT_AT,
                    version_for_upgrade=VERSION_FOR_UPGRADE)))
            else:
                responder._reply(404)
            return
        if responder.method != "POST":
            responder._reply(501)
            return
        # Tenant check BEFORE parking: parked routes drop their headers
        # (release_request), so this is the one place the credential
        # exists.  Pooled routes re-resolve in _route_post (headers are
        # kept), which also stamps the binding onto submitted tasks.
        if self._tenant_binding(getattr(responder, "headers", None)) \
                is _TENANT_DENIED:
            responder._reply(403, _DENIED_BODY)
            return
        path, body = responder.path, responder.request.body
        if path == "/local/acquire_quota":
            self._acquire_quota_parked(responder, body)
            return
        task_type = self.registry.for_wait(path)
        if task_type is not None:
            self._wait_parked(responder, task_type, body)
            return
        self._aio.submit(self._route_post_pooled, responder, path, body)

    def _route_post_pooled(self, responder, path: str,
                           body: bytes) -> None:  # ytpu: responder(responder)
        try:
            self._route_post(responder, path, body)
        except Exception:
            logger.exception("error handling %s", path)
            # A route that replied and then raised must not fire a
            # second 500 into the settled stream.
            if not responder.replied:
                responder._reply(500)

    def _acquire_quota_parked(self, responder, body: bytes) -> None:  # ytpu: untrusted(body)  # ytpu: responder(responder)
        req = _from_json(api.local.AcquireQuotaRequest, body)
        deadline_timer = []

        def on_grant(ok: bool) -> None:
            # The continuation won: its deadline timer must die with it
            # (async-timer-leak discipline).  The box is filled after
            # acquire_async returns; an inline grant simply leaves the
            # timer to fire waiter.expire as a no-op at the deadline.
            if deadline_timer:
                deadline_timer[0].cancel()
            if ok:
                responder._reply(200,
                                 _to_json(api.local.AcquireQuotaResponse()))
            else:
                # Same pacing contract as the threaded route: the
                # caller already waited its window server-side.
                responder._reply(503,
                                 _to_json(api.local.AcquireQuotaResponse()),
                                 retry_after_s=0.5)

        responder.release_request()  # parked: keep the continuation only
        waiter = self.monitor.acquire_async(
            req.requestor_pid, req.lightweight_task, on_grant)
        # The deadline half of the parked continuation: a loop timer,
        # not a polling thread (same clamp as the threaded route).
        deadline_timer.append(self._aio.call_later(
            clamp_wait_s(req.milliseconds_to_wait), waiter.expire))

    def _wait_parked(self, responder, task_type, body: bytes) -> None:  # ytpu: untrusted(body)  # ytpu: responder(responder)
        req = _from_json(task_type.wait_request_cls, body)
        task_id = req.task_id
        deadline_timer = []

        def on_done(result) -> None:
            if responder.replied or result is None:
                return
            # We are going to reply: the deadline timer dies now
            # instead of pinning this closure until the window ends.
            if deadline_timer:
                deadline_timer[0].cancel()
            # Response assembly (multi-chunk join of possibly-multi-MB
            # outputs) belongs on the pool, not the loop.
            self._aio.submit(self._finish_wait_pooled, responder,
                             task_type, task_id, result)

        if not self.dispatcher.wait_for_task_async(task_id, on_done):
            responder._reply(404)
            return
        responder.release_request()  # parked: keep the continuation only

        def on_deadline() -> None:
            # Still running at the poll window's end: 503 + Retry-After,
            # client re-polls (threaded-route semantics).  The
            # completion continuation racing us is settled by the
            # reply-once responder.
            if not self.dispatcher.is_known(task_id):
                responder._reply(404)
            else:
                responder._reply(503, retry_after_s=0.5)

        # ONE clamp: the deadline timer derives from the same
        # clamp_wait_s(..., 10.0) the threaded route's blocking wait
        # uses, so both front ends time out identically.
        deadline_timer.append(self._aio.call_later(
            clamp_wait_s(req.milliseconds_to_wait, 10.0), on_deadline))

    def _finish_wait_pooled(self, responder, task_type, task_id: int,
                            result) -> None:  # ytpu: responder(responder)
        resp, out_chunks = task_type.build_wait_response(result)
        payload = multi_chunk.make_multi_chunk_payload(
            [_to_json(resp)] + list(out_chunks))
        # Free only if OUR reply won: when the deadline timer already
        # answered 503, the client never saw this result and will
        # re-poll for it — freeing here would turn that into a 404.
        if responder._reply(200, payload,
                            content_type="application/octet-stream"):
            self.dispatcher.free_task(task_id)

    # -- routing -------------------------------------------------------------

    def _route_post(self, handler, path: str, body: bytes) -> None:  # ytpu: untrusted(body)  # ytpu: responder(handler)
        binding = self._tenant_binding(
            getattr(handler, "headers", None))
        if binding is _TENANT_DENIED:
            handler._reply(403, _DENIED_BODY)
            return
        if path == "/local/ask_to_leave":
            handler._reply(200, _to_json(api.local.AskToLeaveResponse()))
            self.on_leave()
            return
        if path == "/local/acquire_quota":
            req = _from_json(api.local.AcquireQuotaRequest, body)
            # Clamp the client-supplied window: an unbounded value
            # parked this serving thread (and its quota waiter slot)
            # for arbitrary time.  Clients long-poll and re-ask.
            ok = self.monitor.wait_for_running_new_task_permission(
                req.requestor_pid, req.lightweight_task,
                clamp_wait_s(req.milliseconds_to_wait))
            if ok:
                handler._reply(200,
                               _to_json(api.local.AcquireQuotaResponse()))
            else:
                # The machine is saturated and the caller already waited
                # its full window; come back after a beat, not instantly.
                handler._reply(503,
                               _to_json(api.local.AcquireQuotaResponse()),
                               retry_after_s=0.5)
            return
        if path == "/local/release_quota":
            req = _from_json(api.local.ReleaseQuotaRequest, body)
            self.monitor.drop_task_permission(req.requestor_pid)
            handler._reply(200, _to_json(api.local.ReleaseQuotaResponse()))
            return
        if path == "/local/set_file_digest":
            req = _from_json(api.local.SetFileDigestRequest, body)
            self.digest_cache.set(req.file_desc.path, req.file_desc.size,
                                  req.file_desc.timestamp, req.digest)
            handler._reply(200, _to_json(api.local.SetFileDigestResponse()))
            return
        if path == "/local/jit_cache_get":
            self._jit_cache_get(handler, body, binding)
            return
        if path == "/local/jit_cache_put":
            self._jit_cache_put(handler, body, binding)
            return
        task_type = self.registry.for_submit(path)
        if task_type is not None:
            self._submit_task(handler, task_type, body, binding)
            return
        task_type = self.registry.for_wait(path)
        if task_type is not None:
            self._wait_for_task(handler, task_type, body)
            return
        handler._reply(404)

    # -- generic task submit/wait (one flow for every registered kind) -------

    def _submit_task(self, handler, task_type, body: bytes,
                     binding=None) -> None:  # ytpu: untrusted(body)  # ytpu: responder(handler)
        # Views: the (possibly multi-MB) attachment chunk stays a view
        # into the request body all the way to the servant RPC.
        chunks = multi_chunk.try_parse_multi_chunk_views(body)
        if not chunks or len(chunks) != 2:
            handler._reply(400, task_type.bad_chunks_error)
            return
        req = _from_json(task_type.submit_request_cls, bytes(chunks[0]))
        try:
            task = task_type.make_task(req, chunks[1])
        except Exception as e:
            err = task_type.submit_error(e)
            if err is None:
                raise
            handler._reply(400, err)
            return
        if binding is not None:
            # Instance-level stamp of the VERIFIED identity (never the
            # request body): cache domain, two-level fairness, tier
            # fan-out rights (doc/tenancy.md).
            task.tenant_id = binding.tenant_id
            task.tenant_tier = binding.tier
            task.tenant_key_secret = binding.key_secret
            task.tenant_weight = binding.weight
            task.tenant_fanout_cap = (binding.spec.fanout_cap
                                      or tier_fanout_cap(binding.tier))
        try:
            task_id = self.dispatcher.queue_task(task)
        except TenantOverBudget as e:
            # Budget refusal is backpressure, not an error: same 503 +
            # Retry-After contract the quota and long-poll routes use,
            # so existing client backoff handles it unchanged.
            handler._reply(503, _DENIED_BUDGET_BODY,
                           retry_after_s=e.retry_after_ms / 1000.0)
            return
        # Every submit response is {task_id}; the registered response
        # classes share the field by convention.
        handler._reply(200, _to_json(
            api.local.SubmitCxxTaskResponse(task_id=task_id)))

    def _wait_for_task(self, handler, task_type, body: bytes) -> None:  # ytpu: untrusted(body)  # ytpu: responder(handler)
        req = _from_json(task_type.wait_request_cls, body)
        result = self.dispatcher.wait_for_task(
            req.task_id, clamp_wait_s(req.milliseconds_to_wait, 10.0))
        if result is None:
            if not self.dispatcher.is_known(req.task_id):
                handler._reply(404)
            else:
                handler._reply(503, retry_after_s=0.5)
            return
        resp, out_chunks = task_type.build_wait_response(result)
        self.dispatcher.free_task(req.task_id)
        handler._reply(
            200,
            multi_chunk.make_multi_chunk_payload(
                [_to_json(resp)] + list(out_chunks)),
            content_type="application/octet-stream")

    # -- persistent-compile-cache shim routes --------------------------------

    def _jit_cache_get(self, handler, body: bytes,
                       binding=None) -> None:  # ytpu: untrusted(body)  # ytpu: responder(handler)
        req = _from_json(api.jit.JitCacheGetRequest, body)
        if self.cache_reader is None or not req.key:
            handler._reply(404)
            return
        secret = binding.key_secret if binding is not None else ""
        data = self.cache_reader.try_read(shim_cache_key(req.key, secret))
        if data is None:
            handler._reply(404)
            return
        handler._reply(
            200,
            multi_chunk.make_multi_chunk_payload(
                [_to_json(api.jit.JitCacheGetResponse()), data]),
            content_type="application/octet-stream")

    def _jit_cache_put(self, handler, body: bytes,
                       binding=None) -> None:  # ytpu: untrusted(body)  # ytpu: responder(handler)
        chunks = multi_chunk.try_parse_multi_chunk_views(body)
        if not chunks or len(chunks) != 2:
            handler._reply(400, b'{"error":"expect json+value chunks"}')
            return
        req = _from_json(api.jit.JitCachePutRequest, bytes(chunks[0]))
        if self.cache_writer is None or not req.key:
            handler._reply(404)
            return
        secret = binding.key_secret if binding is not None else ""
        self.cache_writer.async_write(shim_cache_key(req.key, secret),
                                      bytes(chunks[1]))
        handler._reply(200, _to_json(api.jit.JitCachePutResponse()))
