"""Language-extensible distributed-task SPI (delegate side).

Parity with reference yadcc/daemon/local/distributed_task.h: the
dispatcher state machine is language-agnostic; a task type supplies its
cache key, dedup digest, how to start itself on a chosen servant, and
how to digest the servant's completion into a client-facing result.
(The reference's internal versions also shipped Java/Scala tasks over
this same seam — common_flags.cc version ledger.)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class TaskResult:
    exit_code: int = -1
    standard_output: bytes = b""
    standard_error: bytes = b""
    # file key (extension) -> zstd-compressed bytes.
    files: Dict[str, bytes] = field(default_factory=dict)
    patches: Dict[str, List[Tuple[int, int, bytes]]] = field(
        default_factory=dict)
    # Provenance counters (reference distributed_task_dispatcher.h:222-224).
    from_cache: bool = False
    reused_existing: bool = False
    # Fan-out parents only (jit/fanout.py): one ChildVerdict per child,
    # in submission order — the partial-hit / partial-failure contract
    # surfaces these to the client verbatim (doc/workloads.md).
    verdicts: List = field(default_factory=list)


class DistributedTask:
    """SPI; implementations: CxxCompilationTask, JitCompilationTask
    (more workloads ride the same seam — see
    daemon/local/task_registry.py for how a new kind is wired in).

    Implementations must expose `requestor_pid` (0 = unknown) for the
    dispatcher's orphan-kill timer, and a class-level `kind` string
    (stable, lowercase) used for per-workload stats and diagnostics."""

    kind = "unknown"

    # Fan-out parents (jit/fanout.py) set this True and implement
    # expand_children()/reduce() instead of the servant-facing methods;
    # the dispatcher routes them through its fan-out path, where every
    # child is a normal DistributedTask of the same kind.
    is_fanout = False

    # Weighted-fair grant admission (doc/robustness.md): grants are
    # handed out fair-share across fairness keys, weighted by this.  A
    # task kind may override either (e.g. a build-session id instead of
    # a pid, or a lower weight for bulk background work).
    fairness_weight = 1.0

    # Verified tenant identity (doc/tenancy.md), stamped onto INSTANCES
    # by the delegate HTTP surface after credential verification — never
    # taken from the request body.  Class-level defaults are the
    # single-tenant/legacy mode: no tenant, shared cache domain, full
    # fairness weight at the (degenerate, single-entry) tenant level.
    tenant_id = ""
    tenant_tier = ""
    tenant_key_secret = ""
    tenant_weight = 1.0
    # Fan-out width cap for this submission (0 = global default);
    # derived from the tenant's tier/spec at the HTTP surface.
    tenant_fanout_cap = 0

    def fairness_key(self) -> str:
        """Requestor identity for fair grant hand-out.  Default: the
        submitting process — every implementation exposes
        ``requestor_pid`` (it already must, for the orphan-kill timer).
        With tenancy enabled this is the WITHIN-tenant key; the tenant
        level above it is ``fairness_tenant()`` (two-level stride,
        daemon/local/fair_admission.py)."""
        return str(getattr(self, "requestor_pid", 0))

    def fairness_tenant(self) -> str:
        """Tenant identity for the outer stride level; "" = the shared
        legacy tenant.  A bare PID collides across hosts once delegates
        multiplex tenants — the tenant id disambiguates, and the PID
        stays meaningful as the within-tenant key."""
        return self.tenant_id

    # Cache policy (reference distributed_task.h:36 CacheControl):
    CACHE_DISALLOW = 0  # never read, never fill
    CACHE_ALLOW = 1     # read and fill
    CACHE_REFILL = 2    # skip the read, (re)fill on completion — used
    #                     to rebuild a suspect cache without trusting it

    def get_cache_setting(self) -> int:
        raise NotImplementedError

    def get_cache_key(self) -> Optional[str]:
        """None when this task must bypass the cache."""
        raise NotImplementedError

    def get_digest(self) -> str:
        """Cluster-wide dedup digest."""
        raise NotImplementedError

    def get_env_digest(self) -> str:
        raise NotImplementedError

    def start_task(self, channel, token: str, grant_id: int) -> int:
        """Issue Queue*Task on the servant; returns the servant task id."""
        raise NotImplementedError

    def parse_servant_output(self, resp, attachment: bytes) -> TaskResult:
        raise NotImplementedError

    def parse_cache_entry(self, data: bytes) -> Optional[TaskResult]:
        raise NotImplementedError
