"""Delegate-side C++ compilation task.

Parity with reference yadcc/daemon/local/distributed_task/
cxx_compilation_task.cc:47-150: validates the client's submission,
resolves the compiler's digest through the FileDigestCache (the daemon
may not be able to read the client's compiler — the client reports the
digest via /local/set_file_digest when asked), carries the
zstd-compressed preprocessed source, and rebuilds the client-facing
response (files + patch locations) from either a servant completion or
a cache entry.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ... import api
from ...common.limits import checked_attachment
from .. import cache_format, packing
from ..cache_format import get_cache_key
from ..task_digest import get_cxx_task_digest
from .distributed_task import DistributedTask, TaskResult


class NeedCompilerDigest(Exception):
    """The compiler's digest is unknown; the client must report it
    (mapped to HTTP 400 on /local/submit_cxx_task, after which the
    client calls /local/set_file_digest and retries — reference
    compilation_saas.cc:176-194)."""


@dataclass
class CxxCompilationTask(DistributedTask):
    requestor_pid: int
    source_path: str
    source_digest: str
    invocation_arguments: str
    cache_control: int  # 0 off, 1 on, 2 = refill (skip reads, still fill)
    compiler_digest: str
    # bytes-like: the HTTP layer hands a view into the request body, so
    # the source is never copied between loopback receive and RPC send.
    compressed_source: bytes
    ignore_timestamp_macros: bool = False

    kind = "cxx"

    def get_cache_setting(self) -> int:
        if self.cache_control in (self.CACHE_DISALLOW, self.CACHE_ALLOW,
                                  self.CACHE_REFILL):
            return self.cache_control
        return self.CACHE_ALLOW

    def get_cache_key(self) -> Optional[str]:
        if self.get_cache_setting() == self.CACHE_DISALLOW:
            return None
        return get_cache_key(self.compiler_digest,
                             self.invocation_arguments,
                             self.source_digest,
                             tenant_secret=self.tenant_key_secret)

    def get_digest(self) -> str:
        return get_cxx_task_digest(self.compiler_digest,
                                   self.invocation_arguments,
                                   self.source_digest)

    def get_env_digest(self) -> str:
        return self.compiler_digest

    def start_task(self, channel, token: str, grant_id: int) -> int:
        req = api.daemon.QueueCxxCompilationTaskRequest(
            token=token,
            task_grant_id=grant_id,
            source_path=self.source_path,
            invocation_arguments=self.invocation_arguments,
            compression_algorithm=api.daemon.COMPRESSION_ALGORITHM_ZSTD,
            disallow_cache_fill=self.cache_control <= 0,
            ignore_timestamp_macros=self.ignore_timestamp_macros,
        )
        req.env_desc.compiler_digest = self.compiler_digest
        # The servant derives the fill key in the same tenant domain
        # (env_desc.tenant_scope rides the daemon-token-authenticated
        # delegate->servant channel; doc/tenancy.md).
        req.env_desc.tenant_scope = self.tenant_key_secret
        resp, _ = channel.call(
            "ytpu.DaemonService", "QueueCxxCompilationTask", req,
            api.daemon.QueueCxxCompilationTaskResponse,
            attachment=self.compressed_source, timeout=30.0)
        return resp.task_id

    def parse_servant_output(self, resp, attachment) -> TaskResult:
        # Views into the reply frame — output files are not copied out
        # of the attachment; they flow into the client-facing response
        # (or the .o write) still backed by the one received buffer.
        files = packing.try_unpack_keyed_buffers_views(attachment) or {}
        patches = {
            pl.file_key: [
                (loc.position, loc.total_size, loc.suffix_to_keep)
                for loc in pl.locations
            ]
            for pl in resp.cxx_info.patches
        }
        return TaskResult(
            exit_code=resp.exit_code,
            standard_output=resp.standard_output,
            standard_error=resp.standard_error,
            files=files,
            patches=patches,
        )

    def parse_cache_entry(self, data) -> Optional[TaskResult]:
        entry = cache_format.try_parse_cache_entry(data)
        if entry is None:
            return None
        return TaskResult(
            exit_code=entry.exit_code,
            standard_output=entry.standard_output,
            standard_error=entry.standard_error,
            files=entry.files,
            patches=entry.patches,
            from_cache=True,
        )


def make_cxx_task(msg: api.local.SubmitCxxTaskRequest,
                  compressed_source: bytes,
                  file_digest_cache) -> CxxCompilationTask:
    """Build a task from the client's /local/submit_cxx_task message,
    resolving the compiler digest; raises NeedCompilerDigest when the
    memo has no entry for the reported (path, size, mtime)."""
    digest = file_digest_cache.try_get(
        msg.compiler.path, msg.compiler.size, msg.compiler.timestamp)
    if digest is None:
        raise NeedCompilerDigest(msg.compiler.path)
    return CxxCompilationTask(
        requestor_pid=msg.requestor_process_id,
        source_path=msg.source_path,
        source_digest=msg.source_digest,
        invocation_arguments=msg.compiler_invocation_arguments,
        cache_control=msg.cache_control,
        compiler_digest=digest,
        # Wire-cap the attachment at intake: no servant will accept a
        # bigger one, so queuing it only burns delegate memory and
        # retries (taint-registry proves every registered kind does
        # this).
        compressed_source=checked_attachment(compressed_source),
        ignore_timestamp_macros=msg.ignore_timestamp_macros,
    )
