"""Local concurrency governor for client processes.

Parity with reference yadcc/daemon/local/local_task_monitor.{h,cc} and
the policy in yadcc/doc/daemon.md:66-71: the daemon hands out run-quota
to local compiler wrappers in two classes — *lightweight* tasks
(preprocessing, which must flow freely so work reaches the cloud fast)
may over-provision to 1.5x cores, while *heavy* tasks (local compiles,
fallbacks) are capped at 0.5x cores.  Quota is keyed by requestor PID
and reclaimed automatically when the PID dies (crashed clients must not
leak quota forever).
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List

_LIGHT_RATIO = 1.5
_HEAVY_RATIO = 0.5


class QuotaWaiter:
    """A parked quota acquisition (aio front end): the continuation
    fires exactly once — with True when quota is claimed for the pid,
    with False when `expire()` (the loop's deadline timer) wins the
    race.  Costs this object in a list, not a serving thread."""

    __slots__ = ("pid", "lightweight", "_on_grant", "_monitor", "_state")

    def __init__(self, monitor: "LocalTaskMonitor", pid: int,
                 lightweight: bool, on_grant: Callable[[bool], None]):
        self._monitor = monitor
        self.pid = pid
        self.lightweight = lightweight
        self._on_grant = on_grant
        self._state = "waiting"  # state moves only under the monitor lock

    def expire(self) -> None:
        """Deadline: if still waiting, answer False (the threaded
        path's timeout semantics)."""
        mon = self._monitor
        with mon._cv:
            if self._state != "waiting":
                return
            self._state = "expired"
            try:
                mon._async_waiters.remove(self)
            except ValueError:
                pass
        self._on_grant(False)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


class LocalTaskMonitor:
    def __init__(self, nprocs: int = 0,
                 pid_prober=_pid_alive,
                 max_heavy_tasks: int = 0,
                 light_ratio: float = _LIGHT_RATIO):
        n = nprocs or os.cpu_count() or 1
        self._light_limit = max(1, int(n * light_ratio))
        # The >=1 floor applies to the override too: a non-positive
        # --max-local-tasks must not block every heavy compile forever.
        self._heavy_limit = max(1, max_heavy_tasks) if max_heavy_tasks \
            else max(1, int(n * _HEAVY_RATIO))
        self._pid_alive = pid_prober
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # pid -> counts per class.
        self._light: Dict[int, int] = defaultdict(int)  # guarded by: self._lock
        self._heavy: Dict[int, int] = defaultdict(int)  # guarded by: self._lock
        # Parked acquisitions (aio front end), FIFO.
        self._async_waiters: List[QuotaWaiter] = []  # guarded by: self._lock

    # -- acquisition ---------------------------------------------------------

    def wait_for_running_new_task_permission(
        self, pid: int, lightweight: bool, timeout_s: float
    ) -> bool:
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while not self._has_room_locked(lightweight):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(timeout=min(remaining, 0.5))
            (self._light if lightweight else self._heavy)[pid] += 1
            return True

    def acquire_async(self, pid: int, lightweight: bool,
                      on_grant: Callable[[bool], None]) -> QuotaWaiter:  # ytpu: responder(on_grant)
        """Parked-continuation twin of
        wait_for_running_new_task_permission (aio front end): claims
        quota and fires ``on_grant(True)`` immediately when there is
        room, otherwise parks a waiter that the next release/reclaim
        wakes.  The caller owns the deadline: schedule
        ``waiter.expire()`` on its loop timer.  ``on_grant`` fires
        exactly once, never under the monitor lock."""
        waiter = QuotaWaiter(self, pid, lightweight, on_grant)
        with self._cv:
            if self._has_room_locked(lightweight):
                (self._light if lightweight else self._heavy)[pid] += 1
                waiter._state = "granted"
            else:
                self._async_waiters.append(waiter)
        if waiter._state == "granted":
            on_grant(True)
        return waiter

    def _claim_async_waiters_locked(self) -> List[QuotaWaiter]:
        """Grant parked waiters while room lasts (FIFO); returns the
        claimed waiters whose callbacks the CALLER fires after
        releasing the lock."""
        claimed: List[QuotaWaiter] = []
        remaining: List[QuotaWaiter] = []
        for w in self._async_waiters:
            # FIFO per class: a heavy waiter out of room must not
            # head-of-line-block a light waiter whose class has room.
            if self._has_room_locked(w.lightweight):
                (self._light if w.lightweight else self._heavy)[w.pid] += 1
                w._state = "granted"
                claimed.append(w)
            else:
                remaining.append(w)
        self._async_waiters[:] = remaining
        return claimed

    def drop_task_permission(self, pid: int) -> None:
        """Clients don't say which class they release; heavy is assumed
        first (it's the scarcer resource)."""
        with self._cv:
            if self._heavy.get(pid, 0) > 0:
                self._heavy[pid] -= 1
                if not self._heavy[pid]:
                    del self._heavy[pid]
            elif self._light.get(pid, 0) > 0:
                self._light[pid] -= 1
                if not self._light[pid]:
                    del self._light[pid]
            self._cv.notify_all()
            claimed = self._claim_async_waiters_locked()
        for w in claimed:
            w._on_grant(True)

    # -- reclamation ---------------------------------------------------------

    def on_reclaim_timer(self) -> int:
        """1s-cadence: reclaim quota held by dead PIDs; returns count."""
        reclaimed = 0
        claimed = []
        with self._cv:
            for table in (self._light, self._heavy):
                for pid in list(table):
                    if not self._pid_alive(pid):
                        reclaimed += table.pop(pid)
            if reclaimed:
                self._cv.notify_all()
                claimed = self._claim_async_waiters_locked()
        for w in claimed:
            w._on_grant(True)
        return reclaimed

    # -- internals -----------------------------------------------------------

    def _has_room_locked(self, lightweight: bool) -> bool:
        if lightweight:
            return sum(self._light.values()) < self._light_limit
        return sum(self._heavy.values()) < self._heavy_limit

    def inspect(self) -> dict:
        with self._lock:
            return {
                "light_limit": self._light_limit,
                "heavy_limit": self._heavy_limit,
                "light_held": sum(self._light.values()),
                "heavy_held": sum(self._heavy.values()),
                "holders": len(set(self._light) | set(self._heavy)),
                "parked_waiters": len(self._async_waiters),
            }
