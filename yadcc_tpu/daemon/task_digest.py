"""Task dedup digest.

Parity with reference yadcc/daemon/task_digest.cc:25-30: identical
compilations are identified by (compiler binary, invocation arguments,
preprocessed source) — all hashed, domain-separated.  Two clients
compiling the same TU anywhere in the cluster produce the same digest,
which drives duplicate-task joining and the cache key.
"""

from __future__ import annotations

from ..common.hashing import digest_keyed

_DOMAIN = "ytpu-cxx-task"
_JIT_DOMAIN = "ytpu-jit-task"
_AOT_DOMAIN = "ytpu-aot-task"
_AUTOTUNE_DOMAIN = "ytpu-autotune-task"


def get_cxx_task_digest(compiler_digest: str, invocation_arguments: str,
                        source_digest: str) -> str:  # ytpu: sanitizes(key-domain)
    return digest_keyed(
        _DOMAIN,
        compiler_digest.encode(),
        invocation_arguments.encode(),
        source_digest.encode(),
    )


def get_jit_task_digest(env_digest: str, compile_options: bytes,
                        computation_digest: str) -> str:  # ytpu: sanitizes(key-domain)
    """Jit analogue of the (compiler, args, source) triple:
    (jit environment, serialized CompileOptions, lowered StableHLO) —
    each the full determinant of the compile's output in its slot.
    Separate domain: a jit task digest can never collide with a cxx one
    even on crafted inputs."""
    return digest_keyed(
        _JIT_DOMAIN,
        env_digest.encode(),
        bytes(compile_options),
        computation_digest.encode(),
    )


def get_aot_task_digest(env_digest: str, topology_digest: str,
                        computation_digest: str) -> str:  # ytpu: sanitizes(key-domain)
    """One AOT fan-out CHILD (a single topology compile): (jit
    environment, topology spec, lowered StableHLO).  The topology
    digest (jit/fanout.py) already covers the per-topology
    CompileOptions, so the triple fully determines the executable.
    Children of one parent differ only in the topology slot — which is
    exactly what makes each independently cacheable and joinable
    cluster-wide."""
    return digest_keyed(
        _AOT_DOMAIN,
        env_digest.encode(),
        topology_digest.encode(),
        computation_digest.encode(),
    )


def get_autotune_task_digest(env_digest: str, slice_digest: str,
                             kernel_digest: str) -> str:  # ytpu: sanitizes(key-domain)
    """One autotune fan-out CHILD (a slice of the config search
    space): (jit environment, config-slice digest, kernel source).
    The cached artifact is the slice's winning-config RECORD, not an
    executable, so the digest deliberately omits anything
    machine-local — two hosts sweeping the same slice of the same
    kernel dedup to one servant sweep."""
    return digest_keyed(
        _AUTOTUNE_DOMAIN,
        env_digest.encode(),
        slice_digest.encode(),
        kernel_digest.encode(),
    )
