"""Task dedup digest.

Parity with reference yadcc/daemon/task_digest.cc:25-30: identical
compilations are identified by (compiler binary, invocation arguments,
preprocessed source) — all hashed, domain-separated.  Two clients
compiling the same TU anywhere in the cluster produce the same digest,
which drives duplicate-task joining and the cache key.
"""

from __future__ import annotations

from ..common.hashing import digest_keyed

_DOMAIN = "ytpu-cxx-task"
_JIT_DOMAIN = "ytpu-jit-task"


def get_cxx_task_digest(compiler_digest: str, invocation_arguments: str,
                        source_digest: str) -> str:  # ytpu: sanitizes(key-domain)
    return digest_keyed(
        _DOMAIN,
        compiler_digest.encode(),
        invocation_arguments.encode(),
        source_digest.encode(),
    )


def get_jit_task_digest(env_digest: str, compile_options: bytes,
                        computation_digest: str) -> str:  # ytpu: sanitizes(key-domain)
    """Jit analogue of the (compiler, args, source) triple:
    (jit environment, serialized CompileOptions, lowered StableHLO) —
    each the full determinant of the compile's output in its slot.
    Separate domain: a jit task digest can never collide with a cxx one
    even on crafted inputs."""
    return digest_keyed(
        _JIT_DOMAIN,
        env_digest.encode(),
        bytes(compile_options),
        computation_digest.encode(),
    )
