"""Compilation cache-entry wire format.

Parity with reference yadcc/daemon/cache_format.cc:35-127: an entry
bundles the compiler's exit code, stdout/stderr, the produced output
files (individually zstd-compressed) and their path-patch locations,
with an integrity digest so a corrupted entry is detected instead of
linking garbage into the user's build.  The digest covers the file
payloads AND the meta fields (exit code, streams, patch offsets): a
flipped patch offset corrupts the object just as surely as a flipped
payload byte.

Layout:  b"YTC2" + u32 meta_len + CacheMeta-JSON + multi_chunk(files)
where CacheMeta.entry_digest = digest(meta-sans-digest + body)

Cache keys are derived from the task digest (reference :56-64), i.e.
compiler + args + preprocessed source.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..common.hashing import digest_bytes
from ..common.multi_chunk import make_multi_chunk, try_parse_multi_chunk
from .task_digest import get_cxx_task_digest

_MAGIC = b"YTC2"
_LEN = struct.Struct("<I")

# Bump the key prefix on any format change: old entries become silent
# misses instead of parse failures (reference cache_format.cc:56-64).
_KEY_PREFIX = "ytpu-cxx2-entry-"


@dataclass
class CacheEntry:
    exit_code: int
    standard_output: bytes
    standard_error: bytes
    # file key (extension like ".o") -> zstd-compressed content.
    files: Dict[str, bytes]
    # file key -> [(position, total_size, suffix_to_keep)].
    patches: Dict[str, List[Tuple[int, int, bytes]]] = field(
        default_factory=dict)


def get_cache_key(compiler_digest: str, invocation_arguments: str,
                  source_digest: str) -> str:
    return _KEY_PREFIX + get_cxx_task_digest(
        compiler_digest, invocation_arguments, source_digest)


def write_cache_entry(entry: CacheEntry) -> bytes:
    file_keys = sorted(entry.files)
    chunks = [entry.files[k] for k in file_keys]
    body = make_multi_chunk(chunks)
    meta = {
        "exit_code": entry.exit_code,
        "stdout_hex": entry.standard_output.hex(),
        "stderr_hex": entry.standard_error.hex(),
        "file_keys": file_keys,
        "patches": {
            k: [[p, t, s.hex()] for p, t, s in v]
            for k, v in entry.patches.items()
        },
    }
    # Digest over the serialized meta (sort_keys: canonical form) plus
    # the body, so every field is integrity-protected.
    canonical = json.dumps(meta, sort_keys=True).encode()
    meta["entry_digest"] = digest_bytes(canonical + body)
    meta_bytes = json.dumps(meta).encode()
    return _MAGIC + _LEN.pack(len(meta_bytes)) + meta_bytes + body


def try_parse_cache_entry(data: bytes) -> Optional[CacheEntry]:
    """None on any corruption — a bad entry must read as a miss."""
    try:
        if not data.startswith(_MAGIC):
            return None
        (meta_len,) = _LEN.unpack_from(data, 4)
        meta_end = 8 + meta_len
        meta = json.loads(data[8:meta_end])
        body = data[meta_end:]
        claimed = meta.pop("entry_digest")
        canonical = json.dumps(meta, sort_keys=True).encode()
        if claimed != digest_bytes(canonical + body):
            return None  # integrity failure (meta or body tampered)
        chunks = try_parse_multi_chunk(body)
        if chunks is None or len(chunks) != len(meta["file_keys"]):
            return None
        return CacheEntry(
            exit_code=meta["exit_code"],
            standard_output=bytes.fromhex(meta["stdout_hex"]),
            standard_error=bytes.fromhex(meta["stderr_hex"]),
            files=dict(zip(meta["file_keys"], chunks)),
            patches={
                k: [(p, t, bytes.fromhex(s)) for p, t, s in v]
                for k, v in meta.get("patches", {}).items()
            },
        )
    except Exception:
        return None
