"""Compilation cache-entry wire format.

Parity with reference yadcc/daemon/cache_format.cc:35-127: an entry
bundles the compiler's exit code, stdout/stderr, the produced output
files (individually zstd-compressed) and their path-patch locations,
with an integrity digest so a corrupted entry is detected instead of
linking garbage into the user's build.  The digest covers the file
payloads AND the meta fields (exit code, streams, patch offsets): a
flipped patch offset corrupts the object just as surely as a flipped
payload byte.

Layout:  b"YTC2" + u32 meta_len + CacheMeta-JSON + multi_chunk(files)
where CacheMeta.entry_digest = digest(meta-sans-digest + body)

Cache keys are derived from the task digest (reference :56-64), i.e.
compiler + args + preprocessed source.  Every key helper then routes
through the tenant-domain separator (tenancy/keys.py): with a tenant
secret the key is HMAC-scoped to that tenant's namespace (cross-tenant
reads and poisons are cryptographically impossible); with the default
empty secret the legacy key passes through byte-identical, which is
what the dataplane parity gate and historical entries rely on.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..common.hashing import new_digest
from ..common.multi_chunk import try_parse_multi_chunk_views
from ..common.payload import Payload
from ..common.hashing import digest_keyed
from ..tenancy.keys import tenant_scoped_key
from .task_digest import (
    get_aot_task_digest,
    get_autotune_task_digest,
    get_cxx_task_digest,
    get_jit_task_digest,
)

_MAGIC = b"YTC2"
_LEN = struct.Struct("<I")

# Bump the key prefix on any format change: old entries become silent
# misses instead of parse failures (reference cache_format.cc:56-64).
_KEY_PREFIX = "ytpu-cxx2-entry-"
# Second workload, own versioned namespace: a jit artifact can never be
# read back as a C++ object file even if key derivation ever collided.
_JIT_KEY_PREFIX = "ytpu-jit1-entry-"
# Workloads 3 & 4 (doc/workloads.md): per-topology AOT executables and
# autotune winning-config records — separate versioned namespaces, same
# two-factor guarantee (prefix + integrity-covered kind field).
_AOT_KEY_PREFIX = "ytpu-aot1-entry-"
_AUTOTUNE_KEY_PREFIX = "ytpu-tune1-entry-"

# Entry kinds.  "cxx" is the wire default and is OMITTED from the
# serialized meta, so every historical entry (and the dataplane A/B
# parity gate against the legacy writer) stays byte-identical.
KIND_CXX = "cxx"
KIND_JIT = "jit"
KIND_AOT = "aot"
KIND_AUTOTUNE = "autotune"


@dataclass
class CacheEntry:
    exit_code: int
    standard_output: bytes
    standard_error: bytes
    # file key (extension like ".o") -> zstd-compressed content
    # (bytes-like: parsed entries hand back views into the entry buffer).
    files: Dict[str, bytes]
    # file key -> [(position, total_size, suffix_to_keep)].
    patches: Dict[str, List[Tuple[int, int, bytes]]] = field(
        default_factory=dict)
    # Workload kind (KIND_*): parsers reject an entry of the wrong kind
    # as a miss, so a task type can only ever consume its own entries.
    kind: str = KIND_CXX


def get_cache_key(compiler_digest: str, invocation_arguments: str,
                  source_digest: str,
                  tenant_secret: str = "") -> str:  # ytpu: sanitizes(key-domain, tenant-domain)
    return tenant_scoped_key(tenant_secret, _KEY_PREFIX + get_cxx_task_digest(
        compiler_digest, invocation_arguments, source_digest))


def get_jit_cache_key(env_digest: str, compile_options: bytes,
                      computation_digest: str,
                      tenant_secret: str = "") -> str:  # ytpu: sanitizes(key-domain, tenant-domain)
    return tenant_scoped_key(
        tenant_secret, _JIT_KEY_PREFIX + get_jit_task_digest(
            env_digest, compile_options, computation_digest))


def get_aot_cache_key(env_digest: str, topology_digest: str,
                      computation_digest: str,
                      tenant_secret: str = "") -> str:  # ytpu: sanitizes(key-domain, tenant-domain)
    """One AOT child's executable: topology-tagged, so a resubmission
    that adds topologies re-reads the hits and compiles only the
    misses (partial-hit reuse, doc/workloads.md)."""
    return tenant_scoped_key(
        tenant_secret, _AOT_KEY_PREFIX + get_aot_task_digest(
            env_digest, topology_digest, computation_digest))


def get_autotune_cache_key(env_digest: str, slice_digest: str,
                           kernel_digest: str,
                           tenant_secret: str = "") -> str:  # ytpu: sanitizes(key-domain, tenant-domain)
    """One autotune child's slice-winner record."""
    return tenant_scoped_key(
        tenant_secret, _AUTOTUNE_KEY_PREFIX + get_autotune_task_digest(
            env_digest, slice_digest, kernel_digest))


def get_autotune_sweep_key(env_digest: str, space_digest: str,
                           kernel_digest: str,
                           tenant_secret: str = "") -> str:  # ytpu: sanitizes(key-domain, tenant-domain)
    """The SWEEP-level winner record — (kernel digest, search-space
    digest, env digest) — filled by the delegate after the reduce, so
    a second host sweeping the identical space gets the final answer
    in one cache read with zero fan-out.  Domain-separated from the
    per-slice child keys: a slice record can never be read back as a
    sweep verdict."""
    return tenant_scoped_key(
        tenant_secret, _AUTOTUNE_KEY_PREFIX + digest_keyed(
            "ytpu-autotune-sweep", env_digest.encode(),
            space_digest.encode(), kernel_digest.encode()))


def write_cache_entry_payload(entry: CacheEntry) -> Payload:
    """Gather form: [magic+len+meta] ++ [chunk header] ++ file buffers.

    The integrity digest is fed incrementally (meta, then the body
    segments) instead of materializing ``canonical + body`` — for a
    multi-MB object that concatenation was a full extra copy of the
    entry just to hash it.  Wire bytes are identical to the historical
    single-buffer writer (parity-tested)."""
    file_keys = sorted(entry.files)
    chunks = [entry.files[k] for k in file_keys]
    # The multi-chunk body = length header + concatenated chunks; keep
    # the header as its own segment so chunks are never copied.
    body_header = ",".join(str(len(c)) for c in chunks).encode() + b"\r\n"
    meta = {
        "exit_code": entry.exit_code,
        "stdout_hex": entry.standard_output.hex(),
        "stderr_hex": entry.standard_error.hex(),
        "file_keys": file_keys,
        "patches": {
            k: [[p, t, s.hex()] for p, t, s in v]
            for k, v in entry.patches.items()
        },
    }
    if entry.kind != KIND_CXX:
        # "cxx" stays implicit (see KIND_CXX note): the kind key is
        # integrity-covered like every other meta field.
        meta["kind"] = entry.kind
    # Digest over the serialized meta (sort_keys: canonical form) plus
    # the body, so every field is integrity-protected.
    h = new_digest()
    h.update(json.dumps(meta, sort_keys=True).encode())
    h.update(body_header)
    for c in chunks:
        h.update(c)
    meta["entry_digest"] = h.hexdigest()
    meta_bytes = json.dumps(meta).encode()
    return Payload.of(_MAGIC + _LEN.pack(len(meta_bytes)) + meta_bytes,
                      body_header, *chunks)


def write_cache_entry(entry: CacheEntry) -> bytes:
    return write_cache_entry_payload(entry).join()


def try_parse_cache_entry(data,
                          expect_kind: str = KIND_CXX
                          ) -> Optional[CacheEntry]:
    """None on any corruption — a bad entry must read as a miss.

    ``expect_kind`` guards cross-workload reads: an entry of another
    kind parses as a miss, not as garbage handed to the wrong consumer
    (the key-prefix namespaces should already prevent this; the kind
    check makes it a two-factor guarantee).

    Accepts ``bytes``, a ``memoryview`` (an RPC attachment still backed
    by its frame) or a ``Payload``; file contents come back as views
    into the entry buffer — one digest pass, zero body copies."""
    try:
        if isinstance(data, Payload):
            data = data.join()
        mv = memoryview(data)
        if bytes(mv[:4]) != _MAGIC:
            return None
        (meta_len,) = _LEN.unpack_from(mv, 4)
        meta_end = 8 + meta_len
        meta = json.loads(bytes(mv[8:meta_end]))
        body = mv[meta_end:]
        claimed = meta.pop("entry_digest")
        canonical = json.dumps(meta, sort_keys=True).encode()
        h = new_digest()
        h.update(canonical)
        h.update(body)
        if claimed != h.hexdigest():
            return None  # integrity failure (meta or body tampered)
        if meta.get("kind", KIND_CXX) != expect_kind:
            return None  # wrong workload's entry: a miss, not data
        chunks = try_parse_multi_chunk_views(body)
        if chunks is None or len(chunks) != len(meta["file_keys"]):
            return None
        return CacheEntry(
            exit_code=meta["exit_code"],
            standard_output=bytes.fromhex(meta["stdout_hex"]),
            standard_error=bytes.fromhex(meta["stderr_hex"]),
            files=dict(zip(meta["file_keys"], chunks)),
            patches={
                k: [(p, t, bytes.fromhex(s)) for p, t, s in v]
                for k, v in meta.get("patches", {}).items()
            },
            kind=meta.get("kind", KIND_CXX),
        )
    except Exception:
        return None
