"""System load and memory sampling.

Parity with reference yadcc/daemon/sysinfo.{h,cc}: a /proc/stat idle-time
ring sampler (61 one-second samples) yielding an N-second processor
loadavg — the kernel's own 1/5/15min loadavg is far too sluggish for
second-granularity scheduling — plus a /proc/meminfo reader.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Deque, Optional, Tuple

_MAX_SAMPLES = 61


def _read_proc_stat() -> Optional[Tuple[float, float]]:
    """(total_jiffies, idle_jiffies) from the aggregate cpu line."""
    try:
        with open("/proc/stat") as fp:
            line = fp.readline()
    except OSError:
        return None
    parts = line.split()
    if not parts or parts[0] != "cpu":
        return None
    vals = [float(x) for x in parts[1:]]
    total = sum(vals)
    idle = vals[3] + (vals[4] if len(vals) > 4 else 0.0)  # idle + iowait
    return total, idle


def read_memory_available() -> int:
    """Bytes, from /proc/meminfo MemAvailable."""
    try:
        with open("/proc/meminfo") as fp:
            for line in fp:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def read_memory_total() -> int:
    try:
        with open("/proc/meminfo") as fp:
            for line in fp:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def read_cgroup_present() -> bool:
    """True when the daemon runs under a constraining cgroup: the host's
    nproc overstates what we may use, so the servant must refuse work
    (reference execution_engine.cc:75-106: v1 parsed, v2 refused; we
    refuse for both — correct and simpler)."""
    try:
        with open("/proc/self/cgroup") as fp:
            for line in fp:
                # Anything other than the root cgroup means containment.
                name = line.strip().rsplit(":", 1)[-1]
                if name not in ("/", "/init.scope", ""):
                    return True
    except OSError:
        return False
    return False


class LoadAverageSampler:
    """Ring of /proc/stat samples; loadavg(n) = busy cores over the last
    n seconds, in whole processors."""

    def __init__(self, nprocs: Optional[int] = None):
        self._nprocs = nprocs or os.cpu_count() or 1
        self._samples: Deque[Tuple[float, float]] = \
            deque(maxlen=_MAX_SAMPLES)  # guarded by: self._lock
        self._lock = threading.Lock()
        self.sample()

    def sample(self) -> None:
        """Call once per second (the daemon's 1s timer)."""
        s = _read_proc_stat()
        if s is not None:
            with self._lock:
                self._samples.append(s)

    def loadavg(self, seconds: int = 15) -> int:
        with self._lock:
            if len(self._samples) < 2:
                return 0
            n = min(seconds + 1, len(self._samples))
            new_total, new_idle = self._samples[-1]
            old_total, old_idle = self._samples[-n]
        dt = new_total - old_total
        if dt <= 0:
            return 0
        busy_frac = 1.0 - (new_idle - old_idle) / dt
        return max(0, round(busy_frac * self._nprocs))

    @property
    def nprocs(self) -> int:
        return self._nprocs
