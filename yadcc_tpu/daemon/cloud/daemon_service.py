"""Servant RPC service + heartbeat pacemaker.

Parity with reference yadcc/daemon/cloud/daemon_service_impl.{h,cc}:
the DaemonService RPC surface (QueueCxxCompilationTask / ReferenceTask /
WaitForCompilationOutput / FreeTask, :61-186) and the 1-second heartbeat
pacemaker (:50-59, :190-242) reporting version, location, priority,
memory, capacity, nprocs, load, compiler environments and running task
digests — and consuming the scheduler's expired-task kill list plus the
rotating daemon-token window.
"""

from __future__ import annotations

import hmac
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ... import api
from ...common.limits import clamp_wait_s
from ...jit.env import JitEnvironment, default_jit_environments
from ...rpc import Channel, RpcContext, RpcError, ServiceSpec
from ...utils.logging import get_logger
from ...version import VERSION_FOR_UPGRADE
from .. import packing
from ..config import DaemonConfig
from ..sysinfo import (
    LoadAverageSampler,
    read_memory_available,
    read_memory_total,
)
from .aot_task import CloudAotCompilationTask
from .autotune_task import CloudAutotuneTask
from .compiler_registry import CompilerRegistry
from .cxx_task import CloudCxxCompilationTask
from .distributed_cache_writer import DistributedCacheWriter
from .execution_engine import (
    ExecutionEngine,
    decide_capacity,
)
from .jit_task import CloudJitCompilationTask

logger = get_logger("daemon.cloud.service")

SERVICE_NAME = "ytpu.DaemonService"


@dataclass
class _TaskResult:
    exit_code: int = 0
    standard_output: bytes = b""
    standard_error: bytes = b""
    files: Dict[str, bytes] = field(default_factory=dict)
    patches: Dict[str, list] = field(default_factory=dict)
    failed_to_start: bool = False


class DaemonService:
    """The servant role of the daemon process."""

    def __init__(
        self,
        config: DaemonConfig,
        *,
        engine: ExecutionEngine,
        registry: CompilerRegistry,
        cache_writer: Optional[DistributedCacheWriter] = None,
        sampler: Optional[LoadAverageSampler] = None,
        allow_poor_machine: bool = True,
        cgroup_present: Optional[bool] = None,
        jit_environments: Optional[List[JitEnvironment]] = None,
    ):
        self.config = config
        self.engine = engine
        self.registry = registry
        self.cache_writer = cache_writer
        # Jit environments this servant compiles for.  None = the
        # default (this host's cpu-backend environment when a jaxlib is
        # importable, nothing otherwise); [] = jit serving disabled.
        # Their digests ride heartbeat env_descs exactly like compiler
        # digests, so the scheduler's env-matched grant pools gate jit
        # grants to version-matching servants with no scheduler change.
        if jit_environments is None:
            jit_environments = default_jit_environments()
        self._jit_envs = list(jit_environments)
        self._jit_env_digests = {e.digest: e for e in self._jit_envs}
        self.sampler = sampler or LoadAverageSampler()
        self._allow_poor = allow_poor_machine
        self._cgroup = cgroup_present
        self._lock = threading.Lock()
        # Tokens delegates may present, as rolled out by the scheduler.
        self._acceptable_tokens: Set[str] = set()  # guarded by: self._lock
        self._results: Dict[int, _TaskResult] = {}  # guarded by: self._lock
        self._beat_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._sched_channel: Optional[Channel] = None
        # Set by attach_frontend when serving on the aio front end;
        # enables the parked WaitForCompilationOutput path.
        self._frontend = None

    # -- wiring ------------------------------------------------------------

    def attach_frontend(self, server) -> None:
        """Hand the service its RPC front end BEFORE spec() so the
        output long-poll can park: the front end supplies the loop
        deadline timers (``call_later``).  Threaded front ends have no
        timer surface — the sync path stays, as A/B and fallback."""
        self._frontend = server if hasattr(server, "call_later") else None

    def spec(self) -> ServiceSpec:
        s = ServiceSpec(SERVICE_NAME)
        s.add("QueueCxxCompilationTask",
              api.daemon.QueueCxxCompilationTaskRequest,
              self.QueueCxxCompilationTask)
        s.add("QueueJitCompilationTask",
              api.jit.QueueJitCompilationTaskRequest,
              self.QueueJitCompilationTask)
        s.add("QueueAotCompilationTask",
              api.fanout.QueueAotCompilationTaskRequest,
              self.QueueAotCompilationTask)
        s.add("QueueAutotuneTask",
              api.fanout.QueueAutotuneTaskRequest,
              self.QueueAutotuneTask)
        s.add("ReferenceTask", api.daemon.ReferenceTaskRequest,
              self.ReferenceTask)
        s.add("WaitForCompilationOutput",
              api.daemon.WaitForCompilationOutputRequest,
              self.WaitForCompilationOutput)
        s.add("FreeTask", api.daemon.FreeDaemonTaskRequest, self.FreeTask)
        if self._frontend is not None and hasattr(
                self.engine, "wait_for_task_async"):
            # aio front end attached: the output long-poll parks ON the
            # accept loop (engine continuation + loop deadline timer)
            # instead of holding a worker thread in
            # engine.wait_for_task.  Only the aio server consults
            # `parked`; the threaded front end keeps the blocking
            # handler above as A/B + fallback.
            s.add_parked("WaitForCompilationOutput",
                         api.daemon.WaitForCompilationOutputRequest,
                         self.WaitForCompilationOutputParked)
        return s

    def _verify(self, token: str) -> None:  # ytpu: sanitizes(authz)
        # Fail CLOSED: until the first heartbeat response delivers the
        # scheduler's rotating token window, this servant serves nobody.
        # An empty set must not accept-all — QueueCxxCompilationTask
        # ultimately runs caller-supplied command lines.
        with self._lock:
            candidates = sorted(self._acceptable_tokens)
        # Timing-safe sweep: compare against EVERY candidate with
        # hmac.compare_digest and no early exit, so response timing
        # reveals neither a prefix match nor which window position
        # matched (the old set-membership probe hashed the attacker's
        # guess, whose comparison cost leaks on collision probing).
        ok = False
        for candidate in candidates:
            if hmac.compare_digest(token, candidate):
                ok = True
        if not ok:
            raise RpcError(api.daemon.DAEMON_STATUS_ACCESS_DENIED,
                           "unrecognized daemon token")

    def set_acceptable_tokens_for_testing(self, tokens) -> None:
        with self._lock:
            self._acceptable_tokens = set(tokens)

    # -- RPC handlers -------------------------------------------------------

    def QueueCxxCompilationTask(self, req, attachment: bytes,
                                ctx: RpcContext):  # ytpu: untrusted(req, attachment)
        self._verify(req.token)
        if req.compression_algorithm != \
                api.daemon.COMPRESSION_ALGORITHM_ZSTD:
            raise RpcError(api.daemon.DAEMON_STATUS_INVALID_ARGUMENT,
                           "only zstd sources accepted")
        compiler = self.registry.try_get_compiler_path(
            req.env_desc.compiler_digest)
        if compiler is None:
            raise RpcError(
                api.daemon.DAEMON_STATUS_ENVIRONMENT_NOT_AVAILABLE,
                req.env_desc.compiler_digest)
        task = CloudCxxCompilationTask(
            compiler_path=compiler,
            compiler_digest=req.env_desc.compiler_digest,
            invocation_arguments=req.invocation_arguments,
            source_path=req.source_path,
            temp_root=self.config.temporary_dir,
            disallow_cache_fill=req.disallow_cache_fill,
            ignore_timestamp_macros=req.ignore_timestamp_macros,
            tenant_scope=req.env_desc.tenant_scope,
        )
        try:
            try:
                task.prepare(attachment)
            except ValueError as e:
                raise RpcError(api.daemon.DAEMON_STATUS_INVALID_ARGUMENT,
                               str(e))

            # Defensive dedup: an identical task already running here
            # can simply be joined (the delegate-side dedup usually
            # catches this first via ReferenceTask).
            existing = self.engine.find_task_by_digest(task.task_digest)
            if existing is not None and \
                    self.engine.reference_task(existing):
                task.workspace.remove()
                return api.daemon.QueueCxxCompilationTaskResponse(
                    task_id=existing)

            def on_completion(task_id: int, output):
                files, patches, cache_entry = task.collect_outputs(output)
                result = _TaskResult(
                    exit_code=output.exit_code,
                    standard_output=output.standard_output,
                    standard_error=output.standard_error,
                    files=files,
                    patches=patches,
                )
                with self._lock:
                    self._results[task_id] = result
                if cache_entry is not None and \
                        self.cache_writer is not None:
                    self.cache_writer.async_write(task.cache_key,
                                                  cache_entry)

            task_id = self.engine.try_queue_task(
                grant_id=req.task_grant_id,
                digest=task.task_digest,
                cmdline=task.cmdline,
                on_completion=on_completion,
                # Compile INSIDE the padded workspace: -g builds then
                # embed it as DW_AT_comp_dir, which patch-location
                # discovery finds and the client rewrites to its own
                # directory — debuggers on the client machine resolve
                # relative source names (reference pads the workspace
                # for exactly this, remote_task/
                # cxx_compilation_task.cc:78-92).
                cwd=task.workspace.path,
            )
            if task_id is None:
                raise RpcError(api.daemon.DAEMON_STATUS_HEAVILY_LOADED,
                               "servant saturated")
        except BaseException:
            # The RAM-backed workspace must die with the failed
            # submission — admission rejections, RPC mapping, and any
            # unexpected engine error alike (a handler crash turns
            # into a status frame upstream; nothing else would ever
            # reclaim /dev/shm space).
            if task.workspace is not None:
                task.workspace.remove()
            raise
        return api.daemon.QueueCxxCompilationTaskResponse(task_id=task_id)

    def _require_jit_env(self, req):
        """Shared intake gate for the worker-subprocess task kinds
        (jit/aot/autotune): zstd attachment + an advertised jit
        environment.  Version gating: grants should only land here for
        digests we advertised, but a direct (or stale-grant)
        submission for an XLA stack we don't serve must be refused,
        not compiled into an artifact the requestor cannot
        deserialize."""
        if req.compression_algorithm != \
                api.daemon.COMPRESSION_ALGORITHM_ZSTD:
            raise RpcError(api.daemon.DAEMON_STATUS_INVALID_ARGUMENT,
                           "only zstd attachments accepted")
        env = self._jit_env_digests.get(req.env_desc.compiler_digest)
        if env is None:
            raise RpcError(
                api.daemon.DAEMON_STATUS_ENVIRONMENT_NOT_AVAILABLE,
                req.env_desc.compiler_digest)
        return env

    def _queue_worker_task(self, task, grant_id: int, attachment):
        """Prepare + queue one worker-subprocess task (jit/aot/
        autotune) on the engine; returns the servant task id.  One
        body for the three kinds: defensive dedup, completion capture,
        cache fill, and the no-leak cleanup contract are identical —
        only the task object differs."""
        try:
            try:
                task.prepare(attachment)
            except ValueError as e:
                raise RpcError(api.daemon.DAEMON_STATUS_INVALID_ARGUMENT,
                               str(e))

            # Defensive dedup, same as cxx: the delegate-side join
            # usually catches duplicate compilations first, but N
            # delegates racing the same cold task can all be granted
            # before any of them shows up in the running-task
            # snapshot.
            existing = self.engine.find_task_by_digest(task.task_digest)
            if existing is not None and \
                    self.engine.reference_task(existing):
                task.workspace.remove()
                return existing

            def on_completion(task_id: int, output):
                files, patches, cache_entry = task.collect_outputs(output)
                result = _TaskResult(
                    exit_code=output.exit_code,
                    standard_output=output.standard_output,
                    standard_error=output.standard_error,
                    files=files,
                    patches=patches,
                )
                with self._lock:
                    self._results[task_id] = result
                if cache_entry is not None and \
                        self.cache_writer is not None:
                    self.cache_writer.async_write(task.cache_key,
                                                  cache_entry)

            task_id = self.engine.try_queue_task(
                grant_id=grant_id,
                digest=task.task_digest,
                cmdline=task.cmdline,
                on_completion=on_completion,
                # The worker needs the package importable from the
                # engine's `sh -c` launch; worker artifacts embed no
                # paths, so no padded workspace (see cloud/jit_task.py).
                env=task.worker_env(),
                cwd=task.workspace.path,
            )
            if task_id is None:
                raise RpcError(api.daemon.DAEMON_STATUS_HEAVILY_LOADED,
                               "servant saturated")
        except BaseException:
            # Same cleanup contract as the cxx handler: no exception
            # path may leak the staged workspace.
            if task.workspace is not None:
                task.workspace.remove()
            raise
        return task_id

    def QueueJitCompilationTask(self, req, attachment: bytes,
                                ctx: RpcContext):  # ytpu: untrusted(req, attachment)
        """Second-workload twin of QueueCxxCompilationTask: an XLA jit
        compile lands on the same engine (admission, refcounts,
        kill-on-lease-expiry) through the same generic wait/free RPC
        surface; only submission is jit-specific."""
        self._verify(req.token)
        env = self._require_jit_env(req)
        task = CloudJitCompilationTask(
            env_digest=env.digest,
            backend=req.backend or env.backend,
            compile_options=req.compile_options,
            claimed_computation_digest=req.computation_digest,
            temp_root=self.config.temporary_dir,
            disallow_cache_fill=req.disallow_cache_fill,
            tenant_scope=req.env_desc.tenant_scope,
        )
        task_id = self._queue_worker_task(task, req.task_grant_id,
                                          attachment)
        return api.jit.QueueJitCompilationTaskResponse(task_id=task_id)

    def QueueAotCompilationTask(self, req, attachment: bytes,
                                ctx: RpcContext):  # ytpu: untrusted(req, attachment)
        """One AOT fan-out CHILD: the jit flow with the topology folded
        into the worker options and the cache identity
        (doc/workloads.md)."""
        self._verify(req.token)
        env = self._require_jit_env(req)
        if req.topology.device_count <= 0 or \
                not req.topology.mesh_shape:
            raise RpcError(api.daemon.DAEMON_STATUS_INVALID_ARGUMENT,
                           "aot submission names no topology")
        task = CloudAotCompilationTask(
            env_digest=env.digest,
            backend=req.backend or env.backend,
            mesh_shape=tuple(req.topology.mesh_shape),
            device_count=req.topology.device_count,
            compile_options=req.topology.compile_options,
            claimed_computation_digest=req.computation_digest,
            temp_root=self.config.temporary_dir,
            disallow_cache_fill=req.disallow_cache_fill,
            tenant_scope=req.env_desc.tenant_scope,
        )
        task_id = self._queue_worker_task(task, req.task_grant_id,
                                          attachment)
        return api.fanout.QueueAotCompilationTaskResponse(
            task_id=task_id)

    def QueueAutotuneTask(self, req, attachment: bytes,
                          ctx: RpcContext):  # ytpu: untrusted(req, attachment)
        """One autotune fan-out CHILD: evaluate a config slice; the
        artifact is the slice's winning-config record
        (doc/workloads.md)."""
        self._verify(req.token)
        env = self._require_jit_env(req)
        task = CloudAutotuneTask(
            env_digest=env.digest,
            backend=req.backend or env.backend,
            configs=list(req.configs),
            claimed_kernel_digest=req.kernel_digest,
            temp_root=self.config.temporary_dir,
            disallow_cache_fill=req.disallow_cache_fill,
            tenant_scope=req.env_desc.tenant_scope,
        )
        task_id = self._queue_worker_task(task, req.task_grant_id,
                                          attachment)
        return api.fanout.QueueAutotuneTaskResponse(task_id=task_id)

    def ReferenceTask(self, req, attachment, ctx):  # ytpu: untrusted(req, attachment)
        self._verify(req.token)
        if not self.engine.reference_task(req.task_id):
            raise RpcError(api.daemon.DAEMON_STATUS_TASK_NOT_FOUND,
                           str(req.task_id))
        return api.daemon.ReferenceTaskResponse()

    def _check_wait_request(self, req) -> None:
        """Validation shared by the sync and parked wait paths."""
        self._verify(req.token)
        if api.daemon.COMPRESSION_ALGORITHM_ZSTD not in list(
                req.acceptable_compression_algorithms or
                [api.daemon.COMPRESSION_ALGORITHM_ZSTD]):
            raise RpcError(api.daemon.DAEMON_STATUS_INVALID_ARGUMENT,
                           "peer cannot accept zstd")

    def _build_output_response(self, task_id: int, output,
                               ctx: RpcContext):
        """Turn a wait outcome into the response, shared by the sync
        and parked paths so their replies stay byte-identical
        (tested).  ``output`` is None while the task still runs."""
        resp = api.daemon.WaitForCompilationOutputResponse()
        if output is None:
            resp.status = api.daemon.COMPILATION_TASK_STATUS_RUNNING
            return resp
        with self._lock:
            result = self._results.get(task_id)
        if result is None:
            resp.status = api.daemon.COMPILATION_TASK_STATUS_FAILED
            return resp
        resp.status = api.daemon.COMPILATION_TASK_STATUS_DONE
        resp.exit_code = result.exit_code
        resp.standard_output = result.standard_output
        resp.standard_error = result.standard_error
        resp.compression_algorithm = api.daemon.COMPRESSION_ALGORITHM_ZSTD
        for ext, locs in result.patches.items():
            pl = resp.cxx_info.patches.add(file_key=ext)
            for pos, total, suffix in locs:
                pl.locations.add(position=pos, total_size=total,
                                 suffix_to_keep=suffix)
        # Gather attachment: the compressed output buffers ride as
        # payload segments; the transport flattens once at the socket.
        ctx.response_attachment = packing.pack_keyed_buffers_payload(
            result.files)
        return resp

    def WaitForCompilationOutput(self, req, attachment, ctx: RpcContext):  # ytpu: untrusted(req, attachment)
        self._check_wait_request(req)
        if not self.engine.is_known(req.task_id):
            resp = api.daemon.WaitForCompilationOutputResponse()
            resp.status = api.daemon.COMPILATION_TASK_STATUS_NOT_FOUND
            return resp
        output = self.engine.wait_for_task(
            req.task_id, clamp_wait_s(req.milliseconds_to_wait, 10.0))
        return self._build_output_response(req.task_id, output, ctx)

    # ytpu: loop-only
    def WaitForCompilationOutputParked(self, req, attachment, ctx,
                                       done):  # ytpu: untrusted(req, attachment)  # ytpu: responder(done)
        """Parked twin of WaitForCompilationOutput (aio front end
        only).  Runs ON the accept loop: validation raises inline,
        then the wait becomes an engine completion continuation plus a
        loop deadline timer.  A servant holding 5k peer waiters holds
        5k of these closures — zero pool threads.  ``done`` is
        reply-once; whichever of completion/deadline fires second is a
        counted no-op."""
        self._check_wait_request(req)
        if not self.engine.is_known(req.task_id):
            resp = api.daemon.WaitForCompilationOutputResponse()
            resp.status = api.daemon.COMPILATION_TASK_STATUS_NOT_FOUND
            done(resp)
            return
        replied: list = []
        deadline_timer: list = []

        def on_output(output) -> None:
            # Completion continuation: the engine's waiter thread (or
            # this loop, when the task already finished).  Response
            # assembly is CPU-only; the attachment pack is the same
            # work the sync path does on a pool thread.
            replied.append(True)
            if deadline_timer:
                deadline_timer[0].cancel()
            done(self._build_output_response(req.task_id, output, ctx))

        def on_deadline() -> None:
            # Same reply the sync path's timed-out wait produces.  Drop
            # our waiter from the engine table first: the peer re-polls
            # with a fresh request, so an expired continuation left
            # behind would accumulate (waiters × re-polls stale
            # closures on one slow compile).  Completion racing the
            # removal is settled by the reply-once responder.
            self.engine.cancel_wait(req.task_id, on_output)
            resp = api.daemon.WaitForCompilationOutputResponse()
            resp.status = api.daemon.COMPILATION_TASK_STATUS_RUNNING
            done(resp)

        if not self.engine.wait_for_task_async(req.task_id, on_output):
            # Freed/GC'd between is_known and registration: the sync
            # path's unknown-id answer.
            resp = api.daemon.WaitForCompilationOutputResponse()
            resp.status = api.daemon.COMPILATION_TASK_STATUS_NOT_FOUND
            done(resp)
            return
        if replied:
            return  # answered inline (task already complete); no timer
        # ONE clamp, shared with the sync path: the deadline timer
        # derives from the same clamp_wait_s(..., 10.0) the blocking
        # engine.wait_for_task call uses, so both front ends time out
        # identically.
        deadline_timer.append(self._frontend.call_later(
            clamp_wait_s(req.milliseconds_to_wait, 10.0), on_deadline))
        if replied:
            # Completion won the race while the timer was being armed;
            # done() already refused the second reply — just reap the
            # timer (cancel is idempotent).
            deadline_timer[0].cancel()

    def FreeTask(self, req, attachment, ctx):  # ytpu: untrusted(req, attachment)
        self._verify(req.token)
        if self.engine.free_task(req.task_id):
            # Fully released: no joined waiter still needs the result.
            with self._lock:
                self._results.pop(req.task_id, None)
        return api.daemon.FreeDaemonTaskResponse()

    # -- heartbeat pacemaker -------------------------------------------------

    def start_heartbeat(self) -> None:
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name="heartbeat", daemon=True)
        self._beat_thread.start()

    def stop_heartbeat(self, graceful_leave: bool = True) -> None:
        self._stop.set()
        if self._beat_thread is not None:
            self._beat_thread.join(timeout=3)
        if graceful_leave:
            try:
                self.heartbeat_once(leaving=True)
            except RpcError:
                pass

    def _beat_loop(self) -> None:
        while not self._stop.wait(timeout=1.0):
            self.sampler.sample()
            try:
                self.heartbeat_once()
            except RpcError as e:
                logger.warning("heartbeat failed: %s", e)

    def _scheduler(self) -> Channel:
        if self._sched_channel is None:
            self._sched_channel = Channel(self.config.scheduler_uri)
        return self._sched_channel

    def heartbeat_once(self, leaving: bool = False) -> None:
        dedicated = self.config.servant_priority_dedicated
        capacity, reason = decide_capacity(
            self.sampler.nprocs, dedicated,
            allow_poor_machine=self._allow_poor,
            cgroup_present=self._cgroup,
        )
        if self.config.max_remote_tasks:
            capacity = min(capacity, self.config.max_remote_tasks) \
                if capacity else 0
        req = api.scheduler.HeartbeatRequest(
            token=self.config.token,
            next_heartbeat_in_ms=0 if leaving else 1000,
            version=VERSION_FOR_UPGRADE,
            location=self.config.location,
            num_processors=self.sampler.nprocs,
            current_load=self.sampler.loadavg(
                self.config.cpu_load_average_seconds),
            priority=(api.scheduler.SERVANT_PRIORITY_DEDICATED if dedicated
                      else api.scheduler.SERVANT_PRIORITY_USER),
            not_accepting_task_reason=reason,
            capacity=capacity if reason == 0 else 0,
            total_memory_in_bytes=read_memory_total(),
            memory_available_in_bytes=read_memory_available(),
        )
        for digest in self.registry.environments():
            req.env_descs.add(compiler_digest=digest)
        # Jit environments travel in the same env_desc list: to the
        # scheduler an environment is an opaque digest, so version-
        # matched jit grant pools come for free.
        for env in self._jit_envs:
            req.env_descs.add(compiler_digest=env.digest)
        for tid, grant_id, digest in self.engine.running_tasks():
            req.running_tasks.add(
                servant_task_id=tid, task_grant_id=grant_id,
                servant_location=self.config.location, task_digest=digest)
        resp, _ = self._scheduler().call(
            "ytpu.SchedulerService", "Heartbeat", req,
            api.scheduler.HeartbeatResponse, timeout=5.0)
        if leaving:
            return
        with self._lock:
            if resp.acceptable_tokens:
                self._acceptable_tokens = set(resp.acceptable_tokens)
        if resp.expired_tasks:
            self.engine.kill_expired_tasks(list(resp.expired_tasks))
        self.engine.gc_completed_tasks()
        # Results must not outlive their engine-side task (the delegate
        # may never call FreeTask — crash, join path, GC race).
        with self._lock:
            self._results = {tid: r for tid, r in self._results.items()
                             if self.engine.is_known(tid)}

    # -- introspection -------------------------------------------------------

    def inspect(self) -> dict:
        return {
            "engine": self.engine.inspect(),
            "compilers": self.registry.environments(),
            "jit_environments": [
                {"backend": e.backend, "jaxlib_version": e.jaxlib_version,
                 "digest": e.digest}
                for e in self._jit_envs
            ],
            "load": self.sampler.loadavg(
                self.config.cpu_load_average_seconds),
            "load_window_s": self.config.cpu_load_average_seconds,
        }
