"""Servant-side autotune slice sweep (one fan-out child).

Evaluates a contiguous slice of a sweep's candidate configs against
the attached kernel and writes the slice's WINNING CONFIG RECORD —
JSON ``{"config": ..., "score": ..., "metric": ..., "evaluated": N}``
— as its one artifact.  The record (not an executable) is what enters
the cache (kind="autotune", ``ytpu-tune1-`` namespace, keyed by
(env, slice digest, kernel digest)), so a second host sweeping the
same slice of the same kernel gets the measurement for free.

Intake discipline is the jit task's verbatim: fused decompress⊕digest,
claimed-digest verification, bounded staged configs, workspace removed
on every exit path.
"""

from __future__ import annotations

import json
import shlex
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ...common import compress
from ...common.multi_chunk import make_multi_chunk
from ...common.payload import Payload
from ...jit.fanout import slice_digest
from .. import cache_format
from ..cache_format import CacheEntry, get_autotune_cache_key
from ..task_digest import get_autotune_task_digest
from .cxx_task import _PACK_EXECUTOR
from .execution_engine import TaskOutput
from .jit_task import _fake_worker, _worker_mem_bytes, \
    worker_subprocess_env
from .temporary import TemporaryDir

# The slice child's one artifact: its winner record.
RECORD_KEY = ".cfg"


@dataclass
class CloudAutotuneTask:
    env_digest: str
    backend: str
    configs: List[str]  # canonical-JSON candidates (this slice)
    claimed_kernel_digest: str
    temp_root: str
    disallow_cache_fill: bool = False
    # Tenant cache domain (env_desc.tenant_scope, doc/tenancy.md).
    tenant_scope: str = ""

    kernel_digest: str = ""
    workspace: Optional[TemporaryDir] = None
    cmdline: str = ""

    # -- prepare -------------------------------------------------------------

    def prepare(self, compressed_kernel: bytes) -> None:  # ytpu: acquires(workspace)
        try:
            kernel, self.kernel_digest = \
                compress.decompress_and_digest(compressed_kernel)
        except (compress.CompressionError, MemoryError, ValueError):
            raise ValueError("kernel attachment is not valid zstd")
        if self.claimed_kernel_digest and \
                self.kernel_digest != self.claimed_kernel_digest:
            raise ValueError("kernel digest mismatch")
        parsed = []
        for c in self.configs:
            try:
                obj = json.loads(c)
            except ValueError:
                obj = None
            if not isinstance(obj, dict):
                raise ValueError("config is not a JSON object")
            parsed.append(obj)
        if not parsed:
            raise ValueError("empty config slice")

        self.workspace = TemporaryDir(self.temp_root, "tune_")
        options = {
            "backend": self.backend,
            "mem_limit_bytes": _worker_mem_bytes(),
            "autotune_configs": parsed,
        }
        with open(f"{self.workspace.path}/request.bin", "wb") as fp:
            fp.write(make_multi_chunk(
                [json.dumps(options, sort_keys=True).encode(),
                 kernel]))
        fake = " --fake" if _fake_worker() else ""
        self.cmdline = (
            f"{shlex.quote(sys.executable)} -m "
            f"yadcc_tpu.jit.compile_worker "
            f"--workspace {shlex.quote(self.workspace.path)}{fake}"
        )

    def worker_env(self) -> dict:
        return worker_subprocess_env()

    @property
    def slice_digest(self) -> str:
        return slice_digest(self.configs)

    @property
    def task_digest(self) -> str:
        return get_autotune_task_digest(self.env_digest,
                                        self.slice_digest,
                                        self.kernel_digest)

    @property
    def cache_key(self) -> str:
        return get_autotune_cache_key(self.env_digest, self.slice_digest,
                                      self.kernel_digest,
                                      tenant_secret=self.tenant_scope)

    # -- completion ----------------------------------------------------------

    def collect_outputs(self, output: TaskOutput) -> Tuple[
        Dict[str, bytes],
        Dict[str, list],
        Optional[Payload],
    ]:
        """(compressed record by key, empty patches, cache-entry
        payload or None); workspace removed on every path."""
        assert self.workspace is not None
        try:
            files: Dict[str, bytes] = {}
            record = None
            if output.exit_code == 0:
                try:
                    with open(f"{self.workspace.path}/artifact.bin",
                              "rb") as fp:
                        record = fp.read()
                except OSError:
                    record = None
            entry_future = None
            if record is not None:
                files[RECORD_KEY] = compress.compress(record)
                if not self.disallow_cache_fill:
                    entry_future = _PACK_EXECUTOR.get().submit(
                        cache_format.write_cache_entry_payload, CacheEntry(
                            exit_code=output.exit_code,
                            standard_output=output.standard_output,
                            standard_error=output.standard_error,
                            files=files,
                            kind=cache_format.KIND_AUTOTUNE,
                        ))
            return files, {}, (entry_future.result()
                               if entry_future is not None else None)
        finally:
            self.workspace.remove()
