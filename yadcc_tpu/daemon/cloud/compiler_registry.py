"""Compiler discovery and digesting.

Parity with reference yadcc/daemon/cloud/compiler_registry.cc:44-166:
scan PATH plus configured extra dirs every 60s for gcc/g++/clang/clang++
binaries, skip build-accelerator wrappers (ccache/distcc/icecc/ytpu
symlinks — executing one of those from a servant would recurse), digest
each real binary, and serve digest -> path lookups for incoming tasks.
"""

from __future__ import annotations

import os
import threading
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from ...common.hashing import digest_file
from ...utils.logging import get_logger

logger = get_logger("daemon.compiler_registry")

_COMPILER_NAMES = ("gcc", "g++", "clang", "clang++", "cc", "c++")
_WRAPPER_MARKERS = ("ccache", "distcc", "icecc", "ytpu", "yadcc")


class CompilerRegistry:
    def __init__(self, extra_dirs: Sequence[str] = ()):
        self._extra_dirs = list(extra_dirs)
        self._lock = threading.Lock()
        self._by_digest: Dict[str, str] = {}
        self._digest_memo: Dict[tuple, str] = {}  # (real, size, mtime)
        self.rescan()

    # -- queries -------------------------------------------------------------

    def try_get_compiler_path(self, digest: str) -> Optional[str]:
        with self._lock:
            return self._by_digest.get(digest)

    def environments(self) -> List[str]:
        with self._lock:
            return sorted(self._by_digest)

    # -- scanning ------------------------------------------------------------

    def rescan(self) -> None:
        """60s-cadence timer body."""
        dirs = os.environ.get("PATH", "").split(os.pathsep) + self._extra_dirs
        found: Dict[str, str] = {}
        for d in dirs:
            if not d:
                continue
            for name in _COMPILER_NAMES:
                p = Path(d) / name
                real = self._resolve_usable(p)
                if real is None:
                    continue
                try:
                    st = os.stat(real)
                    memo_key = (real, st.st_size, int(st.st_mtime))
                    with self._lock:
                        digest = self._digest_memo.get(memo_key)
                    if digest is None:
                        digest = digest_file(real)
                        with self._lock:
                            self._digest_memo[memo_key] = digest
                except OSError:
                    continue
                found.setdefault(digest, str(p))
        with self._lock:
            added = set(found) - set(self._by_digest)
            self._by_digest = found
        for digest in added:
            logger.info("registered compiler %s (%s)", found[digest],
                        digest[:16])

    @staticmethod
    def _resolve_usable(p: Path) -> Optional[str]:
        """Real path of a usable compiler binary; None for wrappers,
        broken symlinks, and non-executables."""
        try:
            if not p.exists() or not os.access(p, os.X_OK):
                return None
            real = p.resolve(strict=True)
        except OSError:
            return None
        lowered = str(real).lower()
        if any(m in lowered for m in _WRAPPER_MARKERS):
            return None
        # A symlink chain passing through a wrapper name also disqualifies.
        hop = p
        for _ in range(16):
            if any(m in hop.name.lower() for m in _WRAPPER_MARKERS):
                return None
            if not hop.is_symlink():
                break
            hop = hop.parent / os.readlink(hop)
        return str(real)
