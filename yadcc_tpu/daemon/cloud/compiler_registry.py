"""Compiler discovery and digesting.

Parity with reference yadcc/daemon/cloud/compiler_registry.cc:44-166:
scan PATH plus configured extra dirs every 60s for gcc/g++/clang/clang++
binaries, skip build-accelerator wrappers (ccache/distcc/icecc/ytpu
symlinks — executing one of those from a servant would recurse), digest
each real binary, and serve digest -> path lookups for incoming tasks.
"""

from __future__ import annotations

import os
import threading
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from ...common.hashing import digest_file
from ...utils.logging import get_logger

logger = get_logger("daemon.compiler_registry")

_COMPILER_NAMES = ("gcc", "g++", "clang", "clang++", "cc", "c++")
_WRAPPER_MARKERS = ("ccache", "distcc", "icecc", "ytpu", "yadcc")


# RHEL devtoolset roots the reference probes unconditionally
# (compiler_registry.cc:224-230).
_DEVTOOLSET_FMT = "/opt/rh/devtoolset-{}/root/bin"


class CompilerRegistry:
    def __init__(self, extra_dirs: Sequence[str] = (),
                 bundle_dirs: Sequence[str] = ()):
        """bundle_dirs: parent directories holding whole toolchain
        bundles; every `<bundle>/*/bin` is scanned like a PATH entry
        (reference --extra_compiler_bundle_dirs,
        compiler_registry.cc:51-56,210-222)."""
        self._extra_dirs = list(extra_dirs)
        self._bundle_dirs = list(bundle_dirs)
        self._lock = threading.Lock()
        self._by_digest: Dict[str, str] = {}  # guarded by: self._lock
        # (real, size, mtime) -> digest
        self._digest_memo: Dict[tuple, str] = {}  # guarded by: self._lock
        self.rescan()

    # -- queries -------------------------------------------------------------

    def try_get_compiler_path(self, digest: str) -> Optional[str]:
        with self._lock:
            return self._by_digest.get(digest)

    def environments(self) -> List[str]:
        with self._lock:
            return sorted(self._by_digest)

    # -- scanning ------------------------------------------------------------

    def rescan(self) -> None:
        """60s-cadence timer body."""
        dirs = os.environ.get("PATH", "").split(os.pathsep) + self._extra_dirs
        dirs += self._enumerate_bundle_bins()
        found: Dict[str, str] = {}
        memo_live = set()
        for d in dirs:
            if not d:
                continue
            for name in _COMPILER_NAMES:
                p = Path(d) / name
                real = self._resolve_usable(p)
                if real is None:
                    continue
                try:
                    st = os.stat(real)
                    memo_key = (real, st.st_size, int(st.st_mtime))
                    with self._lock:
                        digest = self._digest_memo.get(memo_key)
                    if digest is None:
                        digest = digest_file(real)
                        with self._lock:
                            self._digest_memo[memo_key] = digest
                except OSError:
                    continue
                memo_live.add(memo_key)
                found.setdefault(digest, str(p))
        with self._lock:
            added = set(found) - set(self._by_digest)
            self._by_digest = found
            # Self-clean the digest memo: entries for file versions no
            # longer on disk (toolchain upgrades bump mtime/size every
            # rescan) would otherwise accumulate for the daemon's
            # lifetime.
            self._digest_memo = {k: v for k, v in
                                 self._digest_memo.items()
                                 if k in memo_live}
        for digest in added:
            logger.info("registered compiler %s (%s)", found[digest],
                        digest[:16])

    def _enumerate_bundle_bins(self) -> List[str]:
        """`<bundle>/*/bin` for every configured bundle dir, plus the
        reference's unconditional RHEL devtoolset ladder.  Non-dirs and
        unreadable entries are skipped silently, like the reference."""
        out: List[str] = []
        for bundle in self._bundle_dirs:
            try:
                subdirs = sorted(os.listdir(bundle))
            except OSError:
                continue
            for sub in subdirs:
                d = os.path.join(bundle, sub, "bin")
                if os.path.isdir(d):
                    out.append(d)
        for i in range(1, 100):
            d = _DEVTOOLSET_FMT.format(i)
            if os.path.isdir(d):
                out.append(d)
        return out

    @staticmethod
    def _resolve_usable(p: Path) -> Optional[str]:
        """Real path of a usable compiler binary; None for wrappers,
        broken symlinks, and non-executables."""
        try:
            if not p.exists() or not os.access(p, os.X_OK):
                return None
            real = p.resolve(strict=True)
        except OSError:
            return None
        # Wrapper detection matches the BASENAME only (reference
        # IsCompilerWrapper uses EndsWith): a bundle installed under
        # e.g. /opt/yadcc/toolchains must not disqualify every
        # compiler inside it.
        if any(m in real.name.lower() for m in _WRAPPER_MARKERS):
            return None
        # A symlink chain passing through a wrapper name also disqualifies.
        hop = p
        for _ in range(16):
            if any(m in hop.name.lower() for m in _WRAPPER_MARKERS):
                return None
            if not hop.is_symlink():
                break
            hop = hop.parent / os.readlink(hop)
        return str(real)
