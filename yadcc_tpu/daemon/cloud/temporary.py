"""RAII temporary workspaces under the daemon temp root (RAM-disk by
default).  Parity with reference yadcc/daemon/cloud/temporary_dir.{h,cc}."""

from __future__ import annotations

import shutil
from pathlib import Path
from typing import Dict

from ..temp_dir import make_temp_dir


class TemporaryDir:
    def __init__(self, root: str, tag: str = ""):
        self.path = make_temp_dir(root, tag)

    def read_all_files(self) -> Dict[str, bytes]:
        """relative path -> bytes of everything produced inside."""
        rootp = Path(self.path)
        return {
            str(p.relative_to(rootp)): p.read_bytes()
            for p in rootp.rglob("*") if p.is_file()
        }

    def remove(self) -> None:
        shutil.rmtree(self.path, ignore_errors=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.remove()
