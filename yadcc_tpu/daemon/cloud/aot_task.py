"""Servant-side AOT topology compile (one fan-out child).

The jit task's multi-topology twin: identical intake discipline (fused
decompress⊕digest, claimed-digest verification, staged request file),
with the topology spec carried into the compile worker's options — so
the worker builds the executable for exactly the mesh the child was
fanned out for — and into the cache identity (kind="aot" entries in the
``ytpu-aot1-`` namespace, keyed per topology).
"""

from __future__ import annotations

import json
import os
import shlex
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ...common import compress
from ...common.multi_chunk import make_multi_chunk
from ...common.payload import Payload
from .. import cache_format
from ..cache_format import CacheEntry, get_aot_cache_key
from ..task_digest import get_aot_task_digest
from .cxx_task import _PACK_EXECUTOR
from .execution_engine import TaskOutput
from .jit_task import _fake_worker, _worker_mem_bytes, \
    worker_subprocess_env
from .temporary import TemporaryDir

# Same artifact key as the jit workload: a topology child produces one
# serialized executable.
ARTIFACT_KEY = ".xla"


@dataclass
class CloudAotCompilationTask:
    env_digest: str
    backend: str
    mesh_shape: Tuple[int, ...]
    device_count: int
    compile_options: bytes
    claimed_computation_digest: str
    temp_root: str
    disallow_cache_fill: bool = False
    # Tenant cache domain (env_desc.tenant_scope, doc/tenancy.md).
    tenant_scope: str = ""

    computation_digest: str = ""
    workspace: Optional[TemporaryDir] = None
    cmdline: str = ""

    # -- prepare -------------------------------------------------------------

    def prepare(self, compressed_computation: bytes) -> None:  # ytpu: acquires(workspace)
        try:
            computation, self.computation_digest = \
                compress.decompress_and_digest(compressed_computation)
        except (compress.CompressionError, MemoryError, ValueError):
            raise ValueError("StableHLO attachment is not valid zstd")
        if self.claimed_computation_digest and \
                self.computation_digest != self.claimed_computation_digest:
            raise ValueError("computation digest mismatch")

        self.workspace = TemporaryDir(self.temp_root, "aot_")
        options = {
            "backend": self.backend,
            "compile_options_hex": bytes(self.compile_options).hex(),
            "mem_limit_bytes": _worker_mem_bytes(),
            "mesh_shape": list(self.mesh_shape),
            "device_count": self.device_count,
        }
        with open(f"{self.workspace.path}/request.bin", "wb") as fp:
            fp.write(make_multi_chunk(
                [json.dumps(options, sort_keys=True).encode(),
                 computation]))
        fake = " --fake" if _fake_worker() else ""
        self.cmdline = (
            f"{shlex.quote(sys.executable)} -m "
            f"yadcc_tpu.jit.compile_worker "
            f"--workspace {shlex.quote(self.workspace.path)}{fake}"
        )

    def worker_env(self) -> dict:
        return worker_subprocess_env()

    @property
    def topology_digest(self) -> str:
        from ...jit.fanout import TopologySpec

        return TopologySpec(mesh_shape=tuple(self.mesh_shape),
                            device_count=self.device_count,
                            compile_options=bytes(
                                self.compile_options)).digest()

    @property
    def task_digest(self) -> str:
        return get_aot_task_digest(self.env_digest, self.topology_digest,
                                   self.computation_digest)

    @property
    def cache_key(self) -> str:
        return get_aot_cache_key(self.env_digest, self.topology_digest,
                                 self.computation_digest,
                                 tenant_secret=self.tenant_scope)

    # -- completion ----------------------------------------------------------

    def collect_outputs(self, output: TaskOutput) -> Tuple[
        Dict[str, bytes],
        Dict[str, list],
        Optional[Payload],
    ]:
        """Same contract as the jit task: (compressed artifacts, empty
        patches, cache-entry payload or None), workspace removed on
        every path including kill-mid-compile."""
        assert self.workspace is not None
        try:
            files: Dict[str, bytes] = {}
            artifact = None
            if output.exit_code == 0:
                try:
                    with open(f"{self.workspace.path}/artifact.bin",
                              "rb") as fp:
                        artifact = fp.read()
                except OSError:
                    artifact = None
            entry_future = None
            if artifact is not None:
                files[ARTIFACT_KEY] = compress.compress(artifact)
                if not self.disallow_cache_fill:
                    entry_future = _PACK_EXECUTOR.get().submit(
                        cache_format.write_cache_entry_payload, CacheEntry(
                            exit_code=output.exit_code,
                            standard_output=output.standard_output,
                            standard_error=output.standard_error,
                            files=files,
                            kind=cache_format.KIND_AOT,
                        ))
            return files, {}, (entry_future.result()
                               if entry_future is not None else None)
        finally:
            # Compress/pack failures must not leak the staging dir —
            # same contract as the killed-mid-compile case.
            self.workspace.remove()
