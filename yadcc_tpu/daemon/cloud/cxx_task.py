"""Servant-side C++ compilation task.

Parity with reference yadcc/daemon/cloud/remote_task/cxx_compilation_task
.{h,cc} and remote_task.{h,cc}:

* Prepare (:151-194): decompress the attached preprocessed source,
  digest it, scan for timestamp macros (__TIME__/__DATE__/__TIMESTAMP__)
  that make results uncacheable unless -D-overridden (:46-76), create a
  LENGTH-PADDED workspace directory and assemble the command line with
  the servant's own output path.
* Completion (:94-140 + remote_task.cc:47-88): collect produced files,
  locate every occurrence of the padded workspace path embedded in them
  (debug info, coverage notes) and report the byte regions as patch
  locations so the *client* can splice in its real path — which is why
  the workspace path is padded: any shorter client path fits in place.
* On success, pack a cache entry and fill the distributed cache
  asynchronously.

The compile itself is `sh -c "<compiler> <args> -o <ws>/output.o <src>"`
with no network or shared state — pure subprocess work.
"""

from __future__ import annotations

import shlex
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ...common import compress
from ...common.payload import Payload
from .. import cache_format
from ..cache_format import CacheEntry, get_cache_key
from ..task_digest import get_cxx_task_digest
from .execution_engine import TaskOutput
from .temporary import TemporaryDir

# The workspace path is padded to this length so any client path of sane
# length can be patched over it (reference pads to PATH_MAX; 224 keeps
# paths well under common 255-byte component limits while still covering
# realistic client paths).
_PADDED_WORKSPACE_LEN = 224

# Shared with the client's YTPU_WARN_ON_NONCACHEABLE diagnostic, so the
# warning can never disagree with the authoritative decision made here.
from ...common.cacheability import scan_source_cacheability  # noqa: E402,F401


class _PackExecutor:
    """Lazy shared thread pool for servant output packing.

    One small pool per process, shared by every completing task: a TU
    producing several outputs (.o + .gcno + .su under --coverage /
    -fstack-usage) compresses them concurrently instead of serially on
    the waiter thread, and the cache-entry pack overlaps workspace
    cleanup.  Sized small — compression is CPU work and the compile
    subprocesses own most of the machine."""

    def __init__(self, max_workers: int = 4):
        self._max_workers = max_workers
        self._lock = threading.Lock()
        self._pool: Optional[ThreadPoolExecutor] = None  # guarded by: self._lock

    def get(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self._max_workers,
                    thread_name_prefix="output-pack")
            return self._pool


_PACK_EXECUTOR = _PackExecutor()


def _decompress_and_digest(data) -> Tuple[bytes, str]:  # ytpu: sanitizes(size-cap, digest)
    """Module-level seam: the fused single-pass source intake (swapped
    for the two-pass legacy path in dataplane A/B runs)."""
    return compress.decompress_and_digest(data)


def _pack_one_output(content: bytes, needle: bytes) -> Tuple[
        List[Tuple[int, int, bytes]], bytes]:
    """(patch locations, compressed content) for one produced file —
    the unit of work fanned out on the shared pack executor."""
    return find_patch_locations(content, needle), compress.compress(content)


def find_patch_locations(
    data: bytes, needle: bytes
) -> List[Tuple[int, int, bytes]]:
    """All (position, total_size, suffix_to_keep) regions where `needle`
    (the padded workspace path) is embedded in `data`.

    A region runs from the needle's start to the NUL terminating the
    embedded string (debug path strings are NUL-terminated); the suffix
    is whatever followed the workspace path (e.g. b"/src.cc").  The
    client overwrites the region with <client_dir> + suffix + NUL pad.
    """
    out = []
    start = 0
    while True:
        pos = data.find(needle, start)
        if pos < 0:
            break
        end = data.find(b"\x00", pos)
        if end < 0:
            end = len(data)
        suffix = data[pos + len(needle) : end]
        out.append((pos, end - pos, suffix))
        start = pos + 1
    return out


@dataclass
class CloudCxxCompilationTask:
    compiler_path: str
    compiler_digest: str
    invocation_arguments: str
    source_path: str          # client-side path, for diagnostics
    temp_root: str
    disallow_cache_fill: bool = False
    ignore_timestamp_macros: bool = False
    # Tenant cache domain (env_desc.tenant_scope, doc/tenancy.md): the
    # servant's cache fill must land in the SUBMITTING tenant's
    # namespace; "" = legacy shared domain.
    tenant_scope: str = ""

    source: bytes = b""
    source_digest: str = ""
    cacheable: bool = True
    workspace: Optional[TemporaryDir] = None
    cmdline: str = ""
    _source_ext: str = field(default=".ii", init=False)

    # -- prepare -------------------------------------------------------------

    def prepare(self, compressed_source: bytes) -> None:  # ytpu: acquires(workspace)
        # Fused single pass: each decompressed piece is digested as it
        # is produced, instead of materializing the source and then
        # re-scanning all of it for the digest (the attachment arrives
        # as a view into the RPC frame — no copy on the way in either).
        try:
            src, self.source_digest = _decompress_and_digest(
                compressed_source)
        except (compress.CompressionError, MemoryError, ValueError):
            raise ValueError("source attachment is not valid zstd")
        self.source = src
        self.cacheable = (not self.disallow_cache_fill) and (
            self.ignore_timestamp_macros
            or scan_source_cacheability(src, self.invocation_arguments))

        self.workspace = TemporaryDir(self.temp_root, "cxx_")
        # Pad the workspace path by extending the directory name.
        import os

        pad_needed = _PADDED_WORKSPACE_LEN - len(self.workspace.path)
        if pad_needed > 0:
            padded = self.workspace.path + "p" * pad_needed
            os.rename(self.workspace.path, padded)
            self.workspace.path = padded

        # The attachment is already-preprocessed source; tell the
        # compiler so via -x …-cpp-output (when the client preprocessed
        # with -fdirectives-only, it keeps "-fpreprocessed
        # -fdirectives-only" in the forwarded arguments).  Suffix check
        # is case-SENSITIVE: 'Foo.C' is C++ by GCC convention.
        language = "c" if self.source_path.endswith((".c", ".i")) else "c++"
        self._source_ext = ".i" if language == "c" else ".ii"
        src_file = f"{self.workspace.path}/src{self._source_ext}"
        with open(src_file, "wb") as fp:
            fp.write(src)
        self.cmdline = (
            f"{shlex.quote(self.compiler_path)} "
            f"-x {language}-cpp-output "
            f"{self.invocation_arguments} -c "
            f"-o {shlex.quote(self.workspace.path + '/output.o')} "
            f"{shlex.quote(src_file)}"
        )

    @property
    def task_digest(self) -> str:
        return get_cxx_task_digest(self.compiler_digest,
                                   self.invocation_arguments,
                                   self.source_digest)

    @property
    def cache_key(self) -> str:
        return get_cache_key(self.compiler_digest,
                             self.invocation_arguments,
                             self.source_digest,
                             tenant_secret=self.tenant_scope)

    # -- completion ----------------------------------------------------------

    def collect_outputs(self, output: TaskOutput) -> Tuple[
        Dict[str, bytes],
        Dict[str, List[Tuple[int, int, bytes]]],
        Optional[Payload],
    ]:
        """(compressed files by extension, patch locations by extension,
        cache-entry payload or None).  Cleans up the workspace.

        Per-file patch-scan + compression fans out on the shared pack
        executor (a --coverage TU's .o/.gcno/.su pack in parallel); the
        cache-entry pack runs there too, overlapping workspace removal.
        The entry is a gather Payload sharing the compressed file
        buffers — the servant never flattens it (the cache-fill RPC
        joins it once at the socket)."""
        assert self.workspace is not None
        try:
            files: Dict[str, bytes] = {}
            patches: Dict[str, List[Tuple[int, int, bytes]]] = {}
            needle = self.workspace.path.encode()
            if output.exit_code == 0:
                pool = _PACK_EXECUTOR.get()
                jobs = []
                for rel, content in \
                        self.workspace.read_all_files().items():
                    if rel == f"src{self._source_ext}":
                        continue  # the input, not a product
                    ext = "." + rel.split(".", 1)[1] if "." in rel else rel
                    jobs.append((ext, pool.submit(_pack_one_output,
                                                  content, needle)))
                for ext, fut in jobs:
                    locs, compressed = fut.result()
                    if locs:
                        patches[ext] = locs
                    files[ext] = compressed
            entry_future = None
            if output.exit_code == 0 and self.cacheable:
                entry_future = _PACK_EXECUTOR.get().submit(
                    cache_format.write_cache_entry_payload, CacheEntry(
                        exit_code=output.exit_code,
                        standard_output=output.standard_output,
                        standard_error=output.standard_error,
                        files=files,
                        patches=patches,
                    ))
            return files, patches, (entry_future.result()
                                    if entry_future is not None else None)
        finally:
            # A pack failure (pool shutdown mid-stop, compressor error)
            # must still reclaim the RAM-backed workspace — the waiter
            # thread reports the exception, nothing retries this task.
            self.workspace.remove()
