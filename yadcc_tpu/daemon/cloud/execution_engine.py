"""ExecutionEngine: the servant's subprocess farm.

Parity with reference yadcc/daemon/cloud/execution_engine.{h,cc}:

* Capacity policy (:48-162): dedicated servants offer 95% of cores, user
  desktops 40%; machines with <=16 cores ("poor") or running inside a
  constraining cgroup offer zero — their numbers lie or their owners
  need them.
* Admission control (:363-390): a task starts only when concurrency and
  free memory (--min-memory-for-starting-new-task, default 2G) allow.
* Every task runs in its own process group, SIGKILLed wholesale on
  overrun/expiry (:329-343); a dedicated waiter watches each child and
  fires the completion callback (:416-489).
* Tasks are reference-counted: several delegates may wait on one task
  (duplicate-compilation joining), and its output survives until the
  last one frees it (:227-281).
* Grants the scheduler has expired are killed on heartbeat feedback
  (:294-310).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ...utils.logging import get_logger
from ..sysinfo import read_cgroup_present, read_memory_available
from .execute_command import kill_process_group, start_program

logger = get_logger("daemon.execution_engine")

# Reference constants (execution_engine.cc:48-65,124-162).
_DEDICATED_CORE_FRACTION = 0.95
_USER_CORE_FRACTION = 0.40
_POOR_MACHINE_CORES = 16

NOT_ACCEPTING_NONE = 0
NOT_ACCEPTING_USER_INSTRUCTED = 1
NOT_ACCEPTING_POOR_MACHINE = 2
NOT_ACCEPTING_CGROUPS = 3

# Completed tasks are kept for late WaitForCompilationOutput retries,
# then GC'd (reference daemon frees them after a grace period).
_COMPLETED_RETENTION_S = 60.0


def decide_capacity(
    nprocs: int,
    dedicated: bool,
    *,
    allow_poor_machine: bool = False,
    cgroup_present: Optional[bool] = None,
) -> tuple:
    """(capacity, not_accepting_reason)."""
    if cgroup_present is None:
        cgroup_present = read_cgroup_present()
    if cgroup_present:
        return 0, NOT_ACCEPTING_CGROUPS
    if nprocs <= _POOR_MACHINE_CORES and not allow_poor_machine:
        return 0, NOT_ACCEPTING_POOR_MACHINE
    frac = _DEDICATED_CORE_FRACTION if dedicated else _USER_CORE_FRACTION
    return max(1, int(nprocs * frac)), NOT_ACCEPTING_NONE


@dataclass
class TaskOutput:
    exit_code: int
    standard_output: bytes
    standard_error: bytes


@dataclass
class _Task:
    task_id: int
    grant_id: int
    digest: str
    cmdline: str
    # Called as on_completion(task_id, output) from the waiter thread.
    on_completion: Callable[[int, TaskOutput], None]
    proc: object = None
    ref_count: int = 1
    started_at: float = field(default_factory=time.monotonic)
    completed_at: Optional[float] = None
    output: Optional[TaskOutput] = None
    done: threading.Event = field(default_factory=threading.Event)
    # Parked continuations from wait_for_task_async, fired on
    # completion.  Guarded by the engine lock.
    waiters: List[Callable[[TaskOutput], None]] = field(
        default_factory=list)


class ExecutionEngine:
    def __init__(
        self,
        *,
        max_concurrency: int,
        min_memory_for_new_task: int = 2 << 30,
        memory_reader: Callable[[], int] = read_memory_available,
    ):
        self._max_concurrency = max_concurrency
        self._min_memory = min_memory_for_new_task
        self._memory_reader = memory_reader
        self._lock = threading.Lock()
        self._tasks: Dict[int, _Task] = {}  # guarded by: self._lock
        self._next_task_id = 1  # guarded by: self._lock
        self.tasks_run_ever = 0  # guarded by: self._lock
        self._rejected = 0  # guarded by: self._lock

    # -- submission ----------------------------------------------------------

    def try_queue_task(
        self,
        *,
        grant_id: int,
        digest: str,
        cmdline: str,
        on_completion: Callable[[int, TaskOutput], None],
        env: Optional[dict] = None,
        cwd: str = "/",
    ) -> Optional[int]:
        """Start a task now or refuse (admission control).  Returns the
        servant task id, or None when the node is saturated."""
        # Sample memory BEFORE taking the lock: the reader's contract is
        # /proc/meminfo I/O, and every RPC worker thread funnels through
        # this admission check — a slow read under the lock would stall
        # heartbeat reporting (running_tasks) and completions behind it.
        # The check is advisory; a grant-sized TOCTOU window is fine.
        memory_ok = self._memory_reader() >= self._min_memory
        with self._lock:
            running = sum(1 for t in self._tasks.values()
                          if t.completed_at is None)
            if running >= self._max_concurrency:
                self._rejected += 1
                return None
            if not memory_ok:
                self._rejected += 1
                return None
            task = _Task(
                task_id=self._next_task_id,
                grant_id=grant_id,
                digest=digest,
                cmdline=cmdline,
                on_completion=on_completion,
            )
            self._next_task_id += 1
            self._tasks[task.task_id] = task
            self.tasks_run_ever += 1
        try:
            proc = start_program(cmdline, env=env, cwd=cwd)
        except OSError as e:
            with self._lock:
                self._tasks.pop(task.task_id, None)
            logger.error("cannot start %r: %s", cmdline, e)
            return None
        with self._lock:
            task.proc = proc
            # A concurrent kill_expired_tasks()/stop() may have already
            # removed the task while the process was being spawned; the
            # fresh process must not escape untracked.
            killed_meanwhile = task.task_id not in self._tasks
        if killed_meanwhile:
            kill_process_group(proc)
            proc.wait()
            return None
        threading.Thread(
            target=self._wait_for_process, args=(task,),
            name=f"task-waiter-{task.task_id}", daemon=True,
        ).start()
        return task.task_id

    def _wait_for_process(self, task: _Task) -> None:
        stdout, stderr = task.proc.communicate()
        output = TaskOutput(task.proc.returncode, stdout, stderr)
        try:
            task.on_completion(task.task_id, output)
        except Exception:
            logger.exception("completion callback failed for task %d",
                             task.task_id)
        with self._lock:
            task.output = output
            task.completed_at = time.monotonic()
            waiters = task.waiters
            task.waiters = []
        task.done.set()
        # Parked continuations fire AFTER on_completion and done.set():
        # the owning service populates its result table inside
        # on_completion, so by the time a continuation runs the result
        # is ready — same ordering a blocking wait_for_task observes.
        for on_done in waiters:
            try:
                on_done(output)
            except Exception:
                logger.exception(
                    "parked wait continuation failed for task %d",
                    task.task_id)

    # -- querying ------------------------------------------------------------

    def reference_task(self, task_id: int) -> bool:
        """Join a running/completed task (dup-compilation)."""
        with self._lock:
            task = self._tasks.get(task_id)
            if task is None:
                return False
            task.ref_count += 1
            return True

    def find_task_by_digest(self, digest: str) -> Optional[int]:
        with self._lock:
            for t in self._tasks.values():
                if t.digest == digest:
                    return t.task_id
            return None

    def wait_for_task(self, task_id: int,
                      timeout_s: float) -> Optional[TaskOutput]:
        """Long-poll: None while still running (or unknown)."""
        with self._lock:
            task = self._tasks.get(task_id)
        if task is None:
            return None
        task.done.wait(timeout=timeout_s)
        return task.output

    def wait_for_task_async(self, task_id: int, on_done) -> bool:  # ytpu: responder(on_done)  # ytpu: allow(reply-drop)  # unknown id: the False return hands the reply back to the caller, which answers NOT_FOUND (mirrors DistributedTaskDispatcher.wait_for_task_async)
        """Loop-native twin of :meth:`wait_for_task`: registers a
        completion continuation instead of blocking a thread.

        Returns False when the task id is unknown (caller replies
        NOT_FOUND).  Otherwise ``on_done(output)`` fires exactly once —
        immediately (from this thread) when the task already completed,
        else from the task's waiter thread at completion.  A parked
        peer costs this closure, zero pool threads."""
        with self._lock:
            task = self._tasks.get(task_id)
            if task is None:
                return False
            if task.output is None:
                task.waiters.append(on_done)
                return True
            output = task.output
        # Completed already: fire outside the lock (the continuation
        # replies on the RPC front end; never under the engine lock).
        on_done(output)
        return True

    def cancel_wait(self, task_id: int, on_done) -> bool:
        """Deregister a parked continuation whose deadline already
        answered.  Without this, every expired long-poll would sit in
        the waiter table until the task completes (the peer re-polls
        with a FRESH request, so at storm scale one slow compile would
        accumulate waiters × re-polls stale closures, all refused at
        completion).  False when the continuation already left the
        table — completion is firing it concurrently; the reply-once
        responder settles that race."""
        with self._lock:
            task = self._tasks.get(task_id)
            if task is None:
                return False
            try:
                task.waiters.remove(on_done)
                return True
            except ValueError:
                return False

    def is_known(self, task_id: int) -> bool:
        with self._lock:
            return task_id in self._tasks

    def running_tasks(self) -> List[tuple]:
        """[(servant_task_id, grant_id, digest)] for heartbeats."""
        with self._lock:
            return [(t.task_id, t.grant_id, t.digest)
                    for t in self._tasks.values() if t.completed_at is None]

    # -- freeing / killing ---------------------------------------------------

    def free_task(self, task_id: int) -> bool:
        """Drop one reference; True when the task is fully released (so
        the caller may also discard any associated results)."""
        with self._lock:
            task = self._tasks.get(task_id)
            if task is None:
                return True
            task.ref_count -= 1
            if task.ref_count > 0:
                return False
            self._tasks.pop(task_id, None)
        self._kill(task)
        return True

    def kill_expired_tasks(self, expired_grant_ids: List[int]) -> None:
        """Heartbeat feedback: the scheduler disowned these grants
        (reference execution_engine.cc:294-310)."""
        expired = set(expired_grant_ids)
        victims = []
        with self._lock:
            for tid, t in list(self._tasks.items()):
                # Only RUNNING work is killed: a finished compile whose
                # grant lapsed still has a waiter coming for its output
                # (completed retention is the GC timer's job).
                if t.grant_id in expired and t.completed_at is None:
                    victims.append(self._tasks.pop(tid))
        for t in victims:
            logger.warning("killing task %d (grant %d expired)", t.task_id,
                           t.grant_id)
            self._kill(t)

    def gc_completed_tasks(self) -> None:
        """1s-cadence: drop finished tasks nobody freed."""
        cutoff = time.monotonic() - _COMPLETED_RETENTION_S
        with self._lock:
            for tid, t in list(self._tasks.items()):
                if t.completed_at is not None and t.completed_at < cutoff:
                    del self._tasks[tid]

    def stop(self) -> None:
        with self._lock:
            victims = list(self._tasks.values())
            self._tasks.clear()
        for t in victims:
            self._kill(t)

    @staticmethod
    def _kill(task: _Task) -> None:
        if task.proc is not None and task.proc.returncode is None:
            kill_process_group(task.proc)

    # -- introspection -------------------------------------------------------

    def inspect(self) -> dict:
        with self._lock:
            return {
                "max_concurrency": self._max_concurrency,
                "running": sum(1 for t in self._tasks.values()
                               if t.completed_at is None),
                "retained_completed": sum(
                    1 for t in self._tasks.values()
                    if t.completed_at is not None),
                "tasks_run_ever": self.tasks_run_ever,
                "rejected": self._rejected,
                # Parked WaitForCompilationOutput continuations.  A
                # deadline-expired waiter stays registered until the
                # task completes (its reply-once guard makes the late
                # fire a no-op) — same accepted slack as the local
                # dispatcher's waiter table.
                "parked_waiters": sum(len(t.waiters)
                                      for t in self._tasks.values()),
            }
