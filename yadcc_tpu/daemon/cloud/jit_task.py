"""Servant-side XLA jit-compilation task (ExecutionTask analogue).

The jit twin of CloudCxxCompilationTask: prepare decompresses and
digests the attached StableHLO (fused single pass, same as the C++
source intake), verifies the client's claimed computation digest (a
corrupted or forged attachment must fail fast, not poison the cache
under the claimed key), and stages a request file for the compile
worker; completion reads the worker's artifact, compresses it, and
packs a kind="jit" cache entry through the shared zero-copy payload
path.

The compile itself is ``python -m yadcc_tpu.jit.compile_worker`` in its
own process group via the SAME execution engine that runs compilers —
admission control, reference counting, kill-on-lease-expiry and
completed-task GC all come for free.  No path patching: serialized
executables don't embed the workspace path, so the padded-workspace
machinery is unnecessary here (the workspace exists only as the
request/artifact staging area and dies with the task).
"""

from __future__ import annotations

import json
import os
import shlex
import sys
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from ...common import compress
from ...common.multi_chunk import make_multi_chunk
from ...common.payload import Payload
from .. import cache_format
from ..cache_format import CacheEntry, get_jit_cache_key
from ..task_digest import get_jit_task_digest
from .cxx_task import _PACK_EXECUTOR
from .execution_engine import TaskOutput
from .temporary import TemporaryDir

# The one artifact key a jit task produces (the serialized executable);
# a future multi-artifact compile (e.g. dumped HLO for diagnostics)
# adds keys without a format change.
ARTIFACT_KEY = ".xla"

# Default address-space ceiling for the compile worker.  XLA on big
# modules can balloon; a runaway compile must die inside its own
# process, not take the servant down.  Override (or disable with 0) via
# YTPU_JIT_WORKER_MEM_BYTES on the servant.
_DEFAULT_WORKER_MEM_BYTES = 8 << 30


def _worker_mem_bytes() -> int:
    try:
        return int(os.environ.get("YTPU_JIT_WORKER_MEM_BYTES",
                                  _DEFAULT_WORKER_MEM_BYTES))
    except ValueError:
        return _DEFAULT_WORKER_MEM_BYTES


def _fake_worker() -> bool:
    """YTPU_JIT_FAKE_WORKER=1: deterministic pseudo-compiles (cluster
    simulator / CI smoke — exercise the farm, not XLA)."""
    return os.environ.get("YTPU_JIT_FAKE_WORKER", "0") == "1"


def worker_subprocess_env() -> dict:
    """Environment for a compile-worker subprocess: the daemon's own,
    plus the package root on PYTHONPATH (the engine launches via
    ``sh -c`` from the workspace, where bare ``-m yadcc_tpu...`` would
    not resolve).  Shared by every worker-launching task kind (jit,
    aot, autotune)."""
    # __file__ is <root>/yadcc_tpu/daemon/cloud/jit_task.py; the
    # importable root is <root>, the PARENT of the package dir.
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    env = dict(os.environ)
    existing = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = pkg_root + (os.pathsep + existing
                                    if existing else "")
    return env


@dataclass
class CloudJitCompilationTask:
    env_digest: str
    backend: str
    compile_options: bytes
    claimed_computation_digest: str
    temp_root: str
    disallow_cache_fill: bool = False
    # Tenant cache domain (env_desc.tenant_scope, doc/tenancy.md).
    tenant_scope: str = ""

    computation_digest: str = ""
    workspace: Optional[TemporaryDir] = None
    cmdline: str = ""

    # -- prepare -------------------------------------------------------------

    def prepare(self, compressed_computation: bytes) -> None:  # ytpu: acquires(workspace)
        try:
            computation, self.computation_digest = \
                compress.decompress_and_digest(compressed_computation)
        except (compress.CompressionError, MemoryError, ValueError):
            raise ValueError("StableHLO attachment is not valid zstd")
        if self.claimed_computation_digest and \
                self.computation_digest != self.claimed_computation_digest:
            raise ValueError("computation digest mismatch")

        self.workspace = TemporaryDir(self.temp_root, "jit_")
        options = {
            "backend": self.backend,
            "compile_options_hex": bytes(self.compile_options).hex(),
            "mem_limit_bytes": _worker_mem_bytes(),
        }
        with open(f"{self.workspace.path}/request.bin", "wb") as fp:
            fp.write(make_multi_chunk(
                [json.dumps(options, sort_keys=True).encode(),
                 computation]))
        fake = " --fake" if _fake_worker() else ""
        self.cmdline = (
            f"{shlex.quote(sys.executable)} -m "
            f"yadcc_tpu.jit.compile_worker "
            f"--workspace {shlex.quote(self.workspace.path)}{fake}"
        )

    def worker_env(self) -> dict:
        return worker_subprocess_env()

    @property
    def task_digest(self) -> str:
        return get_jit_task_digest(self.env_digest, self.compile_options,
                                   self.computation_digest)

    @property
    def cache_key(self) -> str:
        return get_jit_cache_key(self.env_digest, self.compile_options,
                                 self.computation_digest,
                                 tenant_secret=self.tenant_scope)

    # -- completion ----------------------------------------------------------

    def collect_outputs(self, output: TaskOutput) -> Tuple[
        Dict[str, bytes],
        Dict[str, list],
        Optional[Payload],
    ]:
        """(compressed artifacts by key, empty patches, cache-entry
        payload or None).  Cleans up the workspace — including the
        killed-mid-compile case, where the engine's waiter still fires
        this callback with the SIGKILL exit code and the workspace must
        not leak."""
        assert self.workspace is not None
        try:
            files: Dict[str, bytes] = {}
            artifact = None
            if output.exit_code == 0:
                try:
                    with open(f"{self.workspace.path}/artifact.bin",
                              "rb") as fp:
                        artifact = fp.read()
                except OSError:
                    artifact = None
            entry_future = None
            if artifact is not None:
                files[ARTIFACT_KEY] = compress.compress(artifact)
                if not self.disallow_cache_fill:
                    entry_future = _PACK_EXECUTOR.get().submit(
                        cache_format.write_cache_entry_payload, CacheEntry(
                            exit_code=output.exit_code,
                            standard_output=output.standard_output,
                            standard_error=output.standard_error,
                            files=files,
                            kind=cache_format.KIND_JIT,
                        ))
            return files, {}, (entry_future.result()
                               if entry_future is not None else None)
        finally:
            # Compress/pack failures must not leak the staging dir —
            # same contract as the killed-mid-compile case.
            self.workspace.remove()
