"""Compiler subprocess launcher.

Parity with reference yadcc/daemon/cloud/execute_command.cc:34-84: each
task runs `sh -c <cmdline>` in its own process group (so a runaway
compiler's children die with it), niced to 5 (foreign compiles must not
starve the machine's owner), with stdin closed and stdout/stderr
captured.
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional


def start_program(
    cmdline: str,
    *,
    nice_level: int = 5,
    cwd: str = "/",
    env: Optional[dict] = None,
) -> subprocess.Popen:
    """Launch detached into its own process group; caller owns wait()."""

    def pre_exec():  # runs in the child between fork and exec
        os.setpgid(0, 0)
        try:
            os.nice(nice_level)
        except OSError:
            pass

    return subprocess.Popen(
        ["/bin/sh", "-c", cmdline],
        cwd=cwd,
        env=env,
        stdin=subprocess.DEVNULL,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        preexec_fn=pre_exec,
        start_new_session=False,
    )


def kill_process_group(proc: subprocess.Popen) -> None:
    """SIGKILL the whole group (reference execution_engine.cc:329-343)."""
    try:
        os.killpg(proc.pid, 9)
    except (ProcessLookupError, PermissionError):
        pass
