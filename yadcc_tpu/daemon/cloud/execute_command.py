"""Compiler subprocess launcher.

Parity with reference yadcc/daemon/cloud/execute_command.cc:34-84: each
task runs `sh -c <cmdline>` in its own process group (so a runaway
compiler's children die with it), niced to 5 (foreign compiles must not
starve the machine's owner), with stdin closed and stdout/stderr
captured.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import Optional

# Resolved once: start_program runs once per compile task.
_NICE_BIN = shutil.which("nice")


def start_program(
    cmdline: str,
    *,
    nice_level: int = 5,
    cwd: str = "/",
    env: Optional[dict] = None,
) -> subprocess.Popen:
    """Launch detached into its own process group; caller owns wait().

    Deliberately NO preexec_fn: the daemon process runs jax/grpc worker
    threads, and running Python between fork and exec in a
    multithreaded parent intermittently corrupts the child (observed as
    segfaults under fork pressure).  `start_new_session` does the
    setsid at the C level (a session leader is also a process-group
    leader, so killpg(pid) still nukes the whole tree), and niceness
    comes from the `nice` binary instead of os.nice in the child.
    """
    argv = ["/bin/sh", "-c", cmdline]
    if nice_level and _NICE_BIN:
        # Best-effort niceness, never a hard dependency.
        argv = [_NICE_BIN, "-n", str(nice_level)] + argv
    return subprocess.Popen(
        argv,
        cwd=cwd,
        env=env,
        stdin=subprocess.DEVNULL,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        start_new_session=True,
    )


def kill_process_group(proc: subprocess.Popen) -> None:
    """SIGKILL the whole group (reference execution_engine.cc:329-343)."""
    try:
        os.killpg(proc.pid, 9)
    except (ProcessLookupError, PermissionError):
        pass
