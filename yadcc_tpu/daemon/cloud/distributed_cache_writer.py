"""Fire-and-forget cache filling from the servant.

Parity with reference yadcc/daemon/cloud/distributed_cache_writer.h:39-55:
PutEntry is issued asynchronously — a slow or dead cache server must
never delay returning compilation results to the delegate.
"""

from __future__ import annotations

import threading
from typing import Optional

from ... import api
from ...rpc import Channel, RpcError
from ...utils.logging import get_logger

logger = get_logger("daemon.cache_writer")


class DistributedCacheWriter:
    def __init__(self, cache_server_uri: str, token_provider):
        """token_provider: callable returning the current servant token."""
        self._uri = cache_server_uri
        self._token_provider = token_provider
        self._channel: Optional[Channel] = None  # guarded by: self._lock
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return bool(self._uri)

    def _chan(self) -> Channel:
        with self._lock:
            if self._channel is None:
                self._channel = Channel(self._uri)
            return self._channel

    def async_write(self, key: str, value: bytes) -> None:
        if not self.enabled:
            return
        threading.Thread(
            target=self._write, args=(key, value),
            name="cache-fill", daemon=True,
        ).start()

    def _write(self, key: str, value: bytes) -> None:
        try:
            self._chan().call(
                "ytpu.CacheService", "PutEntry",
                api.cache.PutEntryRequest(token=self._token_provider(),
                                          key=key),
                api.cache.PutEntryResponse,
                attachment=value,
                timeout=10.0,
            )
        except RpcError as e:
            logger.warning("cache fill failed for %s: %s", key, e)
