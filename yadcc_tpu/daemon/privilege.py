"""Privilege dropping.

Parity with reference yadcc/daemon/privilege.cc:27-45 (distcc-inspired):
a daemon started as root must not run compiler subprocesses as root —
drop to the first of ytpu/daemon/nobody that exists before serving.
"""

from __future__ import annotations

import os

from ..utils.logging import get_logger

logger = get_logger("daemon.privilege")

_CANDIDATE_USERS = ("ytpu", "daemon", "nobody")


def drop_privileges() -> None:
    if os.name != "posix" or os.geteuid() != 0:
        return
    import pwd

    for name in _CANDIDATE_USERS:
        try:
            entry = pwd.getpwnam(name)
        except KeyError:
            continue
        os.setgid(entry.pw_gid)
        os.setgroups([entry.pw_gid])
        os.setuid(entry.pw_uid)
        logger.info("dropped privileges to %s (uid %d)", name, entry.pw_uid)
        return
    raise RuntimeError(
        "refusing to serve as root: no unprivileged user available "
        f"(tried {_CANDIDATE_USERS})")
