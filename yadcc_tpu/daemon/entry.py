"""Daemon main: one process, two independent roles.

Parity with reference yadcc/daemon/entry.cc:69-262: a *delegate* serving
local clients over loopback HTTP (:8334) and a *servant* serving peer
daemons over RPC (:8335) — either can be disabled; environment scrubbing
(LC_ALL, GCC_COMPARE_DEBUG, SOURCE_DATE_EPOCH would make outputs differ
across machines and poison the cache); privilege drop; stale temp
cleanup; ordered shutdown.  Run:

    python -m yadcc_tpu.daemon.entry \
        --scheduler-uri grpc://scheduler:8336 \
        --cache-server-uri grpc://cache:8337
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import threading
import time

from ..rpc import make_rpc_server
from ..utils import exposed_vars
from ..utils.inspect_server import InspectServer
from ..utils.logging import get_logger
from .config import DaemonConfig
from .privilege import drop_privileges
from .sysinfo import LoadAverageSampler
from .temp_dir import clean_stale_temp_dirs
from .cloud.compiler_registry import CompilerRegistry
from .cloud.daemon_service import DaemonService
from .cloud.distributed_cache_writer import DistributedCacheWriter
from .cloud.execution_engine import ExecutionEngine, decide_capacity
from .local.config_keeper import ConfigKeeper
from .local.distributed_cache_reader import DistributedCacheReader
from .local.distributed_task_dispatcher import DistributedTaskDispatcher
from .local.file_digest_cache import FileDigestCache
from .local.http_service import LocalHttpService
from .local.local_task_monitor import LocalTaskMonitor
from .local.running_task_keeper import RunningTaskKeeper
from .local.task_grant_keeper import TaskGrantKeeper

logger = get_logger("daemon.entry")

# Vars that make compiler output machine-dependent (reference entry.cc
# env scrub): clear before any compile subprocess inherits them.
_SCRUBBED_ENV = ("LC_ALL", "GCC_COMPARE_DEBUG", "SOURCE_DATE_EPOCH")


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("yadcc-tpu-daemon")
    p.add_argument("--scheduler-uri", default="grpc://127.0.0.1:8336",
                   help="scheduler endpoint(s).  Comma-separated URIs "
                        "are an ordered active,standby failover list "
                        "(dialed through FailoverChannel: on "
                        "transport failure / NOT_SERVING the daemon "
                        "rotates and re-dials under backoff); "
                        "';'-separated groups are federation CELLS, "
                        "each group its own failover list — a "
                        "compiler env's home cell is picked by "
                        "consistent hash on its digest "
                        "(doc/scheduler.md \"Federation\")")
    p.add_argument("--cache-server-uri", default="")
    p.add_argument("--token", default="")
    p.add_argument("--local-port", type=int, default=8334)
    p.add_argument("--serving-port", type=int, default=8335)
    p.add_argument("--inspect-port", type=int, default=9335)
    p.add_argument("--inspect-credential", default="")
    p.add_argument("--location", default="",
                   help="ip:port advertised to the scheduler")
    p.add_argument("--dedicated", action="store_true")
    p.add_argument("--max-remote-tasks", type=int, default=0)
    p.add_argument("--extra-compiler-dirs", default="")
    p.add_argument("--extra-compiler-bundle-dirs", default="",
                   help="parent dirs of whole toolchain bundles; every "
                   "<bundle>/*/bin is scanned (reference "
                   "--extra_compiler_bundle_dirs)")
    p.add_argument("--temporary-dir", default="")
    p.add_argument("--jit-backends", default="auto",
                   help="comma-separated XLA backends this servant "
                        "compiles jit tasks for ('cpu,tpu'); 'auto' = "
                        "cpu iff jaxlib is importable; 'none' disables "
                        "jit serving (doc/jit_offload.md)")
    p.add_argument("--allow-poor-machine", action="store_true",
                   help="serve even with <=16 cores (small test rigs)")
    p.add_argument("--ignore-cgroup-limits", action="store_true",
                   help="serve even inside a cgroup/container; only safe "
                        "when the container really owns its cores")
    p.add_argument("--no-privilege-drop", action="store_true")
    p.add_argument("--max-local-tasks", type=int, default=0,
                   help="heavy-class local quota; 0 = cores/2 "
                        "(reference --max_local_tasks)")
    p.add_argument("--lightweight-ratio", type=float, default=1.5,
                   help="lightweight-class quota as a multiple of cores "
                        "(reference "
                        "--lightweight_local_task_overprovisioning_ratio)")
    def _load_window(v: str) -> int:
        n = int(v)
        if not 1 <= n <= 60:
            # The sampler ring holds 61 one-second samples; outside
            # this range the math silently degrades (0 reports a
            # permanently idle machine and the scheduler over-grants).
            raise argparse.ArgumentTypeError(
                "--cpu-load-average-seconds must be in 1..60")
        return n

    p.add_argument("--cpu-load-average-seconds", type=_load_window,
                   default=15, help="loadavg window reported in "
                                    "heartbeats (1..60)")
    p.add_argument("--compiler-rescan-interval", type=float, default=60.0)
    p.add_argument("--debugging-always-use-servant-at", default="",
                   help="debug only: dial THIS servant for every "
                        "dispatched task instead of the granted one")
    p.add_argument("--rpc-frontend", default="threaded",
                   choices=["threaded", "aio"],
                   help="serving front end for BOTH roles (doc/"
                        "daemon.md \"RPC front end\"): 'threaded' = "
                        "ThreadingHTTPServer + grpc thread pool "
                        "(fallback/A-B baseline); 'aio' = the event-"
                        "loop front end — local long-polls "
                        "(acquire_quota, wait_for_*) park as loop "
                        "continuations, and peer servants are dialed "
                        "aio:// (fleet-wide choice)")
    p.add_argument("--accept-loops", type=int, default=1,
                   help="aio front end only: shard the servant RPC "
                        "accept path across N SO_REUSEPORT event "
                        "loops (doc/daemon.md \"RPC front end\"); "
                        "1 = single loop")
    return p


def _guess_local_ip(scheduler_uri: str) -> str:
    # Multi-URI forms (cell groups ';', failover lists ','): route
    # discovery only needs ONE reachable peer — use the first URI.
    first = scheduler_uri.split(";")[0].split(",")[0].strip()
    target = first.split("://")[-1]
    host, _, port = target.rpartition(":")
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    except OSError:
        return "127.0.0.1"
    try:
        s.connect((host or "8.8.8.8", int(port or 443)))
        return s.getsockname()[0]
    except OSError:
        # The fd must not leak on the failure path: a daemon restarting
        # through flaky DNS used to burn one fd per attempt.
        return "127.0.0.1"
    finally:
        s.close()


def daemon_start(args) -> None:
    from ..utils.device_guard import ensure_backend_or_cpu
    from ..utils.locktrace import install_from_env

    install_from_env()  # YTPU_LOCKTRACE=1: lock-order checking tier
    # The delegate's Bloom batch probe jits lazily on the compile hot
    # path; a wedged accelerator must degrade to CPU kernels, not hang
    # the first cache lookup.
    ensure_backend_or_cpu(logger=logger,
                          expose_path="yadcc/device_platform")
    for var in _SCRUBBED_ENV:
        os.environ.pop(var, None)
    if not args.no_privilege_drop:
        drop_privileges()

    # Federation: a servant BELONGS to one cell — heartbeats, config
    # pulls, and running-task renewal dial only the first ';'-group
    # (its own cell's active,standby failover list).  Only the grant
    # keeper (delegate role) federates across all cells, homing each
    # compiler env by digest.
    cell_uri = args.scheduler_uri.split(";")[0].strip()
    config = DaemonConfig(
        scheduler_uri=cell_uri,
        cache_server_uri=args.cache_server_uri,
        token=args.token,
        serving_port=args.serving_port,
        local_port=args.local_port,
        servant_priority_dedicated=args.dedicated,
        max_remote_tasks=args.max_remote_tasks,
        max_local_tasks=args.max_local_tasks,
        lightweight_overprovisioning_ratio=args.lightweight_ratio,
        debugging_always_use_servant_at=args.debugging_always_use_servant_at,
        cpu_load_average_seconds=args.cpu_load_average_seconds,
        compiler_rescan_interval=args.compiler_rescan_interval,
    )
    if args.temporary_dir:
        config.temporary_dir = args.temporary_dir
    # A missing temp root otherwise surfaces much later as a cryptic
    # FileNotFoundError when the servant prepares its first workspace.
    os.makedirs(config.temporary_dir, exist_ok=True)
    removed = clean_stale_temp_dirs(config.temporary_dir)
    if removed:
        logger.info("removed %d stale temp dirs", removed)

    # ---- servant role ----
    sampler = LoadAverageSampler()
    cgroup_present = False if args.ignore_cgroup_limits else None
    capacity, _ = decide_capacity(sampler.nprocs, args.dedicated,
                                  allow_poor_machine=args.allow_poor_machine,
                                  cgroup_present=cgroup_present)
    registry = CompilerRegistry(
        [d for d in args.extra_compiler_dirs.split(",") if d],
        bundle_dirs=[d for d in
                     args.extra_compiler_bundle_dirs.split(",") if d])
    engine = ExecutionEngine(max_concurrency=max(capacity, 1))
    servant_server = make_rpc_server(args.rpc_frontend,
                                     f"0.0.0.0:{args.serving_port}",
                                     accept_loops=args.accept_loops)
    config.location = args.location or \
        f"{_guess_local_ip(args.scheduler_uri)}:{servant_server.port}"
    config_keeper = ConfigKeeper(cell_uri, args.token)
    # PutEntry authenticates with the daemon's STATIC token (the cache
    # server checks --acceptable-servant-tokens; reference
    # distributed_cache_writer.cc:68 sends FLAGS_token) — NOT the
    # rotating serving-daemon token, which the cache server never sees.
    cache_writer = DistributedCacheWriter(
        args.cache_server_uri, lambda: args.token)
    if args.jit_backends == "auto":
        jit_envs = None  # DaemonService default: cpu iff jaxlib imports
    elif args.jit_backends in ("", "none"):
        jit_envs = []
    else:
        from ..jit.env import local_jit_environment

        jit_envs = [local_jit_environment(b)
                    for b in args.jit_backends.split(",") if b]
    service = DaemonService(
        config, engine=engine, registry=registry, cache_writer=cache_writer,
        sampler=sampler, allow_poor_machine=args.allow_poor_machine,
        cgroup_present=cgroup_present, jit_environments=jit_envs)
    # Before spec(): an aio front end parks WaitForCompilationOutput on
    # the accept loop (engine continuation + loop deadline timer).
    service.attach_frontend(servant_server)
    servant_server.add_service(service.spec())
    servant_server.start()

    # ---- delegate role ----
    grant_keeper = TaskGrantKeeper(args.scheduler_uri, args.token)
    cache_reader = DistributedCacheReader(args.cache_server_uri, args.token)
    running_keeper = RunningTaskKeeper(cell_uri)
    dispatcher = DistributedTaskDispatcher(
        grant_keeper=grant_keeper,
        config_keeper=config_keeper,
        cache_reader=cache_reader,
        running_task_keeper=running_keeper,
        debugging_always_use_servant_at=config.debugging_always_use_servant_at,
        # Fan-out parents fill their reduced verdict (the autotune
        # sweep-level winner record) through the servant role's writer
        # — static token, same as compile-output fills.
        cache_writer=cache_writer,
        # The front end is a fleet-wide choice: an aio daemon's peers
        # serve aio:// too (doc/daemon.md "RPC front end").
        servant_scheme=("aio://" if args.rpc_frontend == "aio"
                        else "grpc://"),
    )
    monitor = LocalTaskMonitor(
        max_heavy_tasks=config.max_local_tasks,
        light_ratio=config.lightweight_overprovisioning_ratio)
    digest_cache = FileDigestCache()
    stop = threading.Event()
    http = LocalHttpService(
        monitor=monitor, digest_cache=digest_cache, dispatcher=dispatcher,
        on_leave=stop.set, port=args.local_port,
        # The jit persistent-compile-cache shim routes: gets through the
        # delegate's Bloom-replicated reader, puts through the servant
        # role's writer (static token, same as compile-output fills).
        cache_reader=cache_reader, cache_writer=cache_writer,
        frontend=args.rpc_frontend)

    config_keeper.start()
    cache_reader.start()
    running_keeper.start()
    service.start_heartbeat()
    http.start()
    inspect = InspectServer(args.inspect_port, args.inspect_credential,
                            frontend=args.rpc_frontend)
    inspect.start()
    exposed_vars.expose("yadcc/daemon/engine", engine.inspect)
    exposed_vars.expose("yadcc/daemon/dispatcher", dispatcher.inspect)
    exposed_vars.expose("yadcc/daemon/monitor", monitor.inspect)
    exposed_vars.expose("yadcc/daemon/cache_reader", cache_reader.inspect)
    # Front-end serving stats: on aio these carry `double_replies` —
    # the runtime half of the reply-once protocol check
    # (doc/static_analysis.md "Async protocol").
    exposed_vars.expose("yadcc/daemon/local_http", http.inspect)
    if hasattr(servant_server, "inspect"):
        exposed_vars.expose("yadcc/daemon/servant_rpc",
                            servant_server.inspect)
    logger.info("daemon up: local HTTP :%d, servant RPC :%d (as %s), "
                "inspect :%d", http.port, servant_server.port,
                config.location, inspect.port)

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    last_rescan = time.monotonic()
    while not stop.is_set():
        time.sleep(1.0)
        dispatcher.on_timer()
        monitor.on_reclaim_timer()
        if time.monotonic() - last_rescan >= config.compiler_rescan_interval:
            registry.rescan()
            last_rescan = time.monotonic()

    logger.info("shutting down")
    service.stop_heartbeat(graceful_leave=True)
    http.stop()
    servant_server.stop()
    inspect.stop()
    for c in (config_keeper, cache_reader, running_keeper, grant_keeper):
        c.stop()
    engine.stop()


def main() -> None:
    daemon_start(build_arg_parser().parse_args())


if __name__ == "__main__":
    main()
