"""Keyed buffer packing for attachments.

Parity with the reference's keyed KV attachment packing
(yadcc/daemon/local/packing.cc, consumed by remote_task.cc:69-75 and the
delegate): output files travel as one attachment holding alternating
key/value chunks in multi-chunk framing.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..common.multi_chunk import make_multi_chunk, try_parse_multi_chunk


def pack_keyed_buffers(buffers: Dict[str, bytes]) -> bytes:
    chunks: List[bytes] = []
    for key in sorted(buffers):
        chunks.append(key.encode())
        chunks.append(buffers[key])
    return make_multi_chunk(chunks)


def try_unpack_keyed_buffers(data: bytes) -> Optional[Dict[str, bytes]]:
    chunks = try_parse_multi_chunk(data)
    if chunks is None or len(chunks) % 2 != 0:
        return None
    out: Dict[str, bytes] = {}
    for i in range(0, len(chunks), 2):
        try:
            key = chunks[i].decode()
        except UnicodeDecodeError:
            return None
        out[key] = chunks[i + 1]
    return out
