"""Keyed buffer packing for attachments.

Parity with the reference's keyed KV attachment packing
(yadcc/daemon/local/packing.cc, consumed by remote_task.cc:69-75 and the
delegate): output files travel as one attachment holding alternating
key/value chunks in multi-chunk framing.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..common.multi_chunk import (make_multi_chunk_payload,
                                  try_parse_multi_chunk_views)
from ..common.payload import Payload, count_copy


def pack_keyed_buffers_payload(buffers: Dict[str, bytes]) -> Payload:
    """Gather form: the value buffers ride as their own segments, so a
    response attachment of N output files costs zero concatenations
    until the socket-boundary join."""
    chunks: List[bytes] = []
    for key in sorted(buffers):
        chunks.append(key.encode())
        chunks.append(buffers[key])
    return make_multi_chunk_payload(chunks)


def pack_keyed_buffers(buffers: Dict[str, bytes]) -> bytes:
    return pack_keyed_buffers_payload(buffers).join()


def try_unpack_keyed_buffers_views(
        data) -> Optional[Dict[str, memoryview]]:
    """Zero-copy unpack: values are views into ``data`` (pinned alive by
    them); keys are decoded (they're tiny)."""
    chunks = try_parse_multi_chunk_views(data)
    if chunks is None or len(chunks) % 2 != 0:
        return None
    out: Dict[str, memoryview] = {}
    for i in range(0, len(chunks), 2):
        try:
            key = bytes(chunks[i]).decode()
        except UnicodeDecodeError:
            return None
        out[key] = chunks[i + 1]
    return out


def try_unpack_keyed_buffers(data) -> Optional[Dict[str, bytes]]:
    views = try_unpack_keyed_buffers_views(data)
    if views is None:
        return None
    count_copy(sum(len(v) for v in views.values()))
    return {k: bytes(v) for k, v in views.items()}
