"""Temporary workspace management.

Parity with reference yadcc/daemon/temp_dir.cc:23 (--temporary_dir
defaults to /dev/shm — compile workspaces are RAM-disk-backed so object
files never touch real disk) and daemon/entry.cc:134-160 (stale
``ytpu_*`` directories from crashed prior runs are removed at startup).
"""

from __future__ import annotations

import os
import shutil
import tempfile
from pathlib import Path

_PREFIX = "ytpu_"


def default_temp_root() -> str:
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


def clean_stale_temp_dirs(root: str) -> int:
    """Remove leftovers from previous daemon incarnations; returns count."""
    removed = 0
    try:
        for p in Path(root).iterdir():
            if p.name.startswith(_PREFIX):
                shutil.rmtree(p, ignore_errors=True)
                removed += 1
    except OSError:
        pass
    return removed


def make_temp_dir(root: str, tag: str = "") -> str:
    return tempfile.mkdtemp(prefix=f"{_PREFIX}{tag}", dir=root)
