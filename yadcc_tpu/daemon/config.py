"""Daemon-wide configuration.

Parity with reference yadcc/daemon/common_flags.{h,cc}: the scheduler
URI (deliberately ONE host — the reference scopes out scheduler HA,
common_flags.cc:19-28, and so do we), the cache-server URI, the access
token, and the protocol version ledger (see yadcc_tpu/version.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .temp_dir import default_temp_root


@dataclass
class DaemonConfig:
    scheduler_uri: str = "grpc://127.0.0.1:8336"
    cache_server_uri: str = ""  # empty: cache disabled
    token: str = ""

    # Servant side.
    serving_port: int = 8335
    location: str = ""  # ip:port advertised to the scheduler
    servant_priority_dedicated: bool = False
    max_remote_tasks: int = 0  # 0: derive from capacity policy

    # Delegate side.
    local_port: int = 8334
    # 0 = derive heavy limit from cores (reference --max_local_tasks).
    max_local_tasks: int = 0
    # Reference --lightweight_local_task_overprovisioning_ratio.
    lightweight_overprovisioning_ratio: float = 1.5
    # Reference --debugging_always_use_servant_at: dial THIS servant
    # for every dispatched task instead of the granted one (grants
    # still come from the scheduler).  Debug/testing only.
    debugging_always_use_servant_at: str = ""

    # Reference --cpu_load_average_seconds / --compiler_rescan_interval.
    cpu_load_average_seconds: int = 15
    compiler_rescan_interval: float = 60.0

    temporary_dir: str = field(default_factory=default_temp_root)
    inspect_port: int = 9335
    inspect_credential: str = ""
