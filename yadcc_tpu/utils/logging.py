"""Leveled logging with rate-limited variants.

Parity with the reference's FLARE_LOG_*_EVERY_SECOND macros
(e.g. yadcc/scheduler/task_dispatcher.cc:150) and the client's
zero-dependency stderr logger (yadcc/client/common/logging.{h,cc})."""

from __future__ import annotations

import logging
import os
import sys
import time
from typing import Dict, Tuple

_configured = False


def get_logger(name: str) -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("YTPU_LOG_LEVEL", "INFO").upper()
        logging.basicConfig(
            stream=sys.stderr,
            level=getattr(logging, level, logging.INFO),
            format="%(asctime)s %(levelname).1s %(name)s] %(message)s",
        )
        _configured = True
    return logging.getLogger(name)


_last_emit: Dict[Tuple[str, str], float] = {}


def log_every_n_seconds(
    logger: logging.Logger, level: int, key: str, msg: str, n: float = 1.0
) -> None:
    now = time.monotonic()
    k = (logger.name, key)
    if now - _last_emit.get(k, -1e9) >= n:
        _last_emit[k] = now
        logger.log(level, msg)
