"""Wedge-proof entry for standalone device tools.

The accelerator tunnel in some environments can hang backend
initialization indefinitely (jax.devices() blocks in PJRT client
creation with no timeout).  Any standalone tool that may touch the
device runs its measurement in a re-exec'd child under a watchdog:

    def main(): ...            # the tool, unchanged
    if __name__ == "__main__":
        guard_device_entry(main)

Parent behavior: re-exec `sys.argv` with a child marker; on watchdog
timeout, kill the child and retry once with YTPU_FORCE_CPU=1 (labeled —
a CPU fallback must never masquerade as a device number).  A child that
*completes* with a non-zero exit propagates that exit unchanged: tool
failures (e.g. trace_replay's policy-divergence exit) are not
infrastructure failures and must not be retried into a different
answer.  bench.py uses the same pattern with its own BENCH_* env knobs
(kept for driver compatibility).
"""

from __future__ import annotations

import os
import subprocess
import sys

_CHILD_MARKER = "YTPU_DEVICE_GUARD_CHILD"


def force_cpu_if_requested() -> bool:
    """Child-side: apply the forced-CPU override before backend init.
    Env vars alone don't work here — the interpreter may have imported
    jax at startup with an accelerator platform preset."""
    if os.environ.get("YTPU_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
        return True
    return False


def running_forced_cpu() -> bool:
    return bool(os.environ.get("YTPU_FORCE_CPU"))


def probe_backend(timeout_s: float) -> bool:
    """True iff a jax backend initializes AND runs one op in a fresh
    subprocess within `timeout_s`.  A wedged accelerator tunnel hangs
    PJRT *inside* the first jit call with no timeout; a subprocess is
    the only safe watchdog — a hung in-process jax call cannot be
    interrupted."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; "
             "jnp.arange(4).sum().block_until_ready(); print('ok')"],
            capture_output=True, text=True, timeout=timeout_s)
        return r.returncode == 0 and "ok" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def ensure_backend_or_cpu(logger=None, expose_path: str = "",
                          probe=None) -> bool:
    """Long-running servers that lazily jit device kernels (scheduler
    policies, daemon/cache Bloom probes) call this at startup: if the
    accelerator backend fails a watchdogged health probe, force the
    CPU host platform in-process — a slower kernel beats a thread
    frozen inside PJRT init holding a state machine hostage.  Returns
    True iff CPU was forced; labels the downgrade via /inspect when
    `expose_path` is given."""
    if force_cpu_if_requested():
        # Operator already ordered CPU (YTPU_FORCE_CPU=1, e.g. on a
        # known-wedged host): skip the probe — it would stall startup
        # for the full timeout against the very tunnel being avoided.
        if expose_path:
            from . import exposed_vars

            exposed_vars.expose(
                expose_path,
                lambda: {"forced_cpu": True, "reason": "YTPU_FORCE_CPU"})
        return True
    timeout_s = float(os.environ.get("YTPU_DEVICE_TIMEOUT", 120))
    if (probe or probe_backend)(timeout_s):
        return False
    import jax

    jax.config.update("jax_platforms", "cpu")
    if logger is not None:
        logger.warning(
            "accelerator backend failed health probe (%ss); device "
            "kernels will compile on the CPU host platform", timeout_s)
    if expose_path:
        from . import exposed_vars

        exposed_vars.expose(
            expose_path,
            lambda: {"forced_cpu": True,
                     "reason": "device backend probe failed"})
    return True


def guard_device_entry(main, *, module: str = "",
                       timeout_env: str = "YTPU_DEVICE_TIMEOUT",
                       default_timeout_s: int = 600) -> None:
    """`module`: dotted name for tools launched via `python -m ...` —
    re-exec'ing the file path directly would break relative imports."""
    if os.environ.get(_CHILD_MARKER):
        force_cpu_if_requested()
        main()
        return

    argv = ([sys.executable, "-m", module, *sys.argv[1:]] if module
            else [sys.executable, *sys.argv])
    timeout = int(os.environ.get(timeout_env, default_timeout_s))
    # The AUTOMATIC forced-CPU fallback is not subject to the tunnel
    # wedge being dodged, but it does pay interpreter + jax-import
    # startup on a possibly loaded machine — give it its own floor so a
    # tight device timeout can't kill the very attempt meant to rescue
    # the run.  An operator who preset YTPU_FORCE_CPU themselves keeps
    # their explicit timeout: the floor exists for the rescue retry,
    # not to second-guess a deliberately bounded CPU-only run.
    cpu_timeout = int(os.environ.get("YTPU_DEVICE_CPU_TIMEOUT",
                                     max(timeout, 60)))
    preset_forced = bool(os.environ.get("YTPU_FORCE_CPU"))
    base_env = dict(os.environ, **{_CHILD_MARKER: "1"})
    attempts = [base_env]
    if not preset_forced:
        attempts.append(dict(base_env, YTPU_FORCE_CPU="1"))
    for env in attempts:
        forced = bool(env.get("YTPU_FORCE_CPU"))
        rescue = forced and not preset_forced
        try:
            r = subprocess.run(argv, env=env,
                               timeout=cpu_timeout if rescue else timeout)
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"device-guard: attempt {'(forced CPU) ' if forced else ''}"
                f"timed out after {cpu_timeout if rescue else timeout}s\n")
            continue
        if forced and r.returncode == 0:
            sys.stderr.write(
                "device-guard: NOTE: result produced on forced CPU — "
                "the accelerator was unavailable\n")
        sys.exit(r.returncode)
    sys.stderr.write("device-guard: no backend produced a result\n")
    sys.exit(3)
