"""Event-loop lag watchdog — the dynamic half of ``await-under-lock``.

The static rule (analysis/asyncproto.py) proves no ``await`` happens
under a held threading lock; this module catches what static analysis
cannot see — a parked continuation, C extension, or accidental blocking
call stalling the serving loop at runtime.  Design mirrors
``utils.locktrace``: a process-wide installable sentinel that tests
wrap around their body and assert clean.

* :func:`register` — ``EventLoopThread.__init__`` registers every loop
  it creates (weakly; dead loops cost nothing).  Loops created while a
  watch session is active are picked up immediately, so module-scoped
  server fixtures and per-test fixtures both land under the watch.
* :func:`installed` — context manager: attaches a self-rearming tick
  (every ``interval_s``) to every registered loop via the threadsafe
  seam and runs a watcher thread that flags any loop whose most recent
  tick is older than ``threshold_s`` (default 250ms).  Violations
  collect on the yielded session; tests assert ``not session.violations``.

The tick runs ON the loop, so a stalled loop (handler doing blocking
I/O, lock convoy, sync RPC) stops ticking and the watcher — a plain
thread — observes the gap.  Stopped/closed loops are skipped, not
flagged: teardown is not lag.
"""

from __future__ import annotations

import threading
import time
import weakref
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

DEFAULT_THRESHOLD_S = 0.25
DEFAULT_INTERVAL_S = 0.05

_lock = threading.Lock()
# loop id -> (weakref to loop, name).  Ids recycle only after the loop
# is collected, at which point the weakref is dead and the entry is
# pruned on the next sweep.
_loops: Dict[int, Tuple[weakref.ref, str]] = {}
_session: Optional["WatchSession"] = None


@dataclass
class Violation:
    loop_name: str
    gap_s: float

    def render(self) -> str:
        return (f"loop '{self.loop_name}' stalled {self.gap_s * 1e3:.0f}ms "
                f"between turns")


class WatchSession:
    """One active watch: per-loop tick timestamps + a watcher thread."""

    def __init__(self, threshold_s: float, interval_s: float):
        self.threshold_s = threshold_s
        self.interval_s = interval_s
        self.violations: List[Violation] = []
        self._last: Dict[int, float] = {}
        self._armed: set = set()
        self._stop = threading.Event()
        self._watcher = threading.Thread(
            target=self._watch, name="looplag-watch", daemon=True)

    # -- loop attachment ---------------------------------------------------

    def attach(self, loop, name: str) -> None:
        lid = id(loop)
        with _lock:
            if lid in self._armed:
                return
            self._armed.add(lid)
            self._last[lid] = time.monotonic()

        def tick() -> None:
            self._last[lid] = time.monotonic()
            if not self._stop.is_set():
                loop.call_later(self.interval_s, tick)

        try:
            loop.call_soon_threadsafe(tick)
        except RuntimeError:
            pass  # loop already closed; the watcher skips it

    # -- the watcher thread ------------------------------------------------

    def _watch(self) -> None:
        while not self._stop.wait(self.interval_s):
            now = time.monotonic()
            with _lock:
                snapshot = list(self._last.items())
                registry = dict(_loops)
            for lid, last in snapshot:
                entry = registry.get(lid)
                loop = entry[0]() if entry else None
                if loop is None or loop.is_closed() or \
                        not loop.is_running():
                    continue
                gap = now - last
                if gap > self.threshold_s:
                    name = entry[1] if entry else "?"
                    self.violations.append(Violation(name, gap))
                    # Re-base so one long stall reports once per
                    # threshold window, not once per watcher turn.
                    self._last[lid] = now

    def start(self) -> None:
        self._watcher.start()

    def stop(self) -> None:
        self._stop.set()
        self._watcher.join(timeout=2.0)


def register(loop, name: str = "aio-loop") -> None:
    """Record a live loop; attach it to the active session if any.
    Called by EventLoopThread at construction — costs a dict entry."""
    with _lock:
        _loops[id(loop)] = (weakref.ref(loop), name)
        # Prune dead entries opportunistically.
        dead = [lid for lid, (ref, _) in _loops.items() if ref() is None]
        for lid in dead:
            _loops.pop(lid, None)
        session = _session
    if session is not None:
        session.attach(loop, name)


@contextmanager
def installed(threshold_s: float = DEFAULT_THRESHOLD_S,
              interval_s: float = DEFAULT_INTERVAL_S):
    """Watch every registered loop for the duration of the block.

    Yields the session; callers assert ``not session.violations``.
    Nested installs are rejected — one watcher owns the registry."""
    global _session
    session = WatchSession(threshold_s, interval_s)
    with _lock:
        if _session is not None:
            raise RuntimeError("looplag session already active")
        _session = session
        existing = [(ref(), name) for ref, name in _loops.values()]
    for loop, name in existing:
        if loop is not None and not loop.is_closed():
            session.attach(loop, name)
    session.start()
    try:
        yield session
    finally:
        session.stop()
        with _lock:
            _session = None
