"""Injectable time source.

The reference tests lease expiry with real 2-second sleeps
(yadcc/scheduler/task_dispatcher_test.cc:110-145); this framework makes
every lease-bearing component take a Clock so tests advance time
virtually and stay fast and deterministic."""

from __future__ import annotations

import threading
import time


class Clock:
    """Real monotonic clock."""

    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class VirtualClock(Clock):
    """Manually-advanced clock for tests."""

    def __init__(self, start: float = 0.0):
        self._now = start  # guarded by: self._lock
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)

    def advance(self, seconds: float) -> None:
        with self._lock:
            self._now += seconds


REAL_CLOCK = Clock()
