"""Process-wide introspection registry.

Parity with flare::ExposedVar as used across the reference: every
long-lived component registers a callable producing a JSON-ish dict, and
each server exposes the merged tree at /inspect/vars (reference
yadcc/doc/debugging.md:26-174 shows sample dumps for the scheduler's
dispatcher, the daemon's dispatcher, the execution engine and the cache)."""

from __future__ import annotations

import json
import threading
from typing import Any, Callable, Dict

_registry: Dict[str, Callable[[], Any]] = {}
_lock = threading.Lock()


def expose(path: str, producer: Callable[[], Any]) -> None:
    """Register a producer under a slash-separated path, e.g.
    "yadcc/task_dispatcher"."""
    with _lock:
        _registry[path] = producer


def unexpose(path: str) -> None:
    with _lock:
        _registry.pop(path, None)


def collect(prefix: str = "") -> Dict[str, Any]:
    """Evaluate all producers under `prefix` into a nested dict."""
    with _lock:
        items = [(p, f) for p, f in _registry.items() if p.startswith(prefix)]
    root: Dict[str, Any] = {}
    for path, producer in items:
        try:
            value = producer()
        except Exception as e:  # producers must never break /inspect
            value = {"error": repr(e)}
        node = root
        parts = path.split("/")
        ok = True
        for part in parts[:-1]:
            nxt = node.setdefault(part, {})
            if not isinstance(nxt, dict):
                # A leaf already occupies this path component; nest the
                # colliding producer under a reserved key rather than
                # clobbering (or crashing on) the existing value.
                nxt = node[part] = {"#value": nxt}
            node = nxt
        leaf = parts[-1]
        if isinstance(node.get(leaf), dict):
            node[leaf]["#value"] = value
        else:
            node[leaf] = value
    return root


def dump_json(prefix: str = "") -> str:
    return json.dumps(collect(prefix), indent=2, sort_keys=True, default=str)
