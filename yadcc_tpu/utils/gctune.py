"""Cyclic-GC control for the latency-critical serving path.

CPython's reference counting reclaims almost everything the dispatch
cycle allocates; the *cyclic* collector exists only for reference
cycles, yet its gen-2 passes stop every thread for multi-millisecond
pauses once the process holds a large live heap (a 5k-servant registry,
jitted executables, RPC machinery).  Those pauses land in the middle of
grant cycles and are exactly the >2ms p99 outliers the BASELINE target
forbids (reference yadcc runs C++ and simply has no such collector;
this is the tpu-native equivalent of that property).

The standard low-latency CPython recipe, packaged:

  * ``freeze()`` the post-startup heap out of the collector's sight —
    startup objects are immortal in a server anyway, and gen-2 pause
    time is proportional to objects *visited*, not garbage found;
  * disable the *automatic* threshold-triggered collector on the
    serving path, so a collection can never preempt a dispatch cycle;
  * collect young generations explicitly from the 1 s maintenance
    sweep — an idle-time pass bounded to the nursery, off the grant
    path — with a rare full pass to cap drift from genuine cycles.

`LatencyGcGuard.start()` is called by the scheduler entry after warmup
(heap fully built), `maintain()` from the same sweep loop that runs
lease expiry.  bench.py wraps its measured loops in `guard()` so the
benchmark measures the configuration production actually serves in.
"""

from __future__ import annotations

import contextlib
import gc

from . import exposed_vars
from .clock import REAL_CLOCK

# A full (gen-2) pass every ~60 s of maintenance calls: long-lived
# cycles (rare: dropped RPC contexts, exception tracebacks) must not
# accumulate forever, but the pass runs on the idle sweep thread, not
# under a grant cycle.
_FULL_PASS_PERIOD_S = 60.0


class LatencyGcGuard:
    """Process-wide: owns the automatic collector's on/off state."""

    def __init__(self, clock=REAL_CLOCK):
        self._clock = clock
        self._active = False
        self._last_full = 0.0
        self._young_passes = 0
        self._full_passes = 0
        self._was_enabled = True
        self._prior_frozen = 0
        exposed_vars.expose("yadcc/gc_guard", self.inspect)

    def start(self) -> None:
        """Call once, after startup/warmup built the long-lived heap."""
        # Snapshot the collector state we are about to override, so
        # stop() restores what the process actually had — a host that
        # deliberately runs with GC off (or with its own frozen set)
        # must not find it force-enabled (or force-unfrozen) after us.
        self._was_enabled = gc.isenabled()
        self._prior_frozen = gc.get_freeze_count()
        gc.collect()          # drain pre-existing garbage first
        gc.freeze()           # startup heap: immortal, stop scanning it
        gc.disable()          # no threshold-triggered pauses hereafter
        self._active = True
        self._last_full = self._clock.now()

    def maintain(self) -> None:
        """Idle-time collection; call from the ~1 s maintenance sweep.
        Young-generation only (bounded, sub-ms), with a rare full pass
        to reclaim genuine long-lived cycles."""
        if not self._active:
            return
        now = self._clock.now()
        if now - self._last_full >= _FULL_PASS_PERIOD_S:
            gc.collect()
            self._last_full = now
            self._full_passes += 1
        else:
            gc.collect(1)     # gen 0+1: the per-cycle allocations
            self._young_passes += 1

    def stop(self) -> None:
        if self._active:
            self._active = False
            if self._was_enabled:
                gc.enable()
            # gc.unfreeze() is all-or-nothing: only safe to undo our
            # freeze when nothing was frozen before start() — otherwise
            # we would thaw objects some other owner pinned on purpose.
            if self._prior_frozen == 0:
                gc.unfreeze()

    def inspect(self) -> dict:
        return {
            "active": self._active,
            "auto_collector_enabled": gc.isenabled(),
            "frozen_objects": gc.get_freeze_count(),
            "young_passes": self._young_passes,
            "full_passes": self._full_passes,
        }


@contextlib.contextmanager
def guard():
    """Scoped variant for benchmarks/tools: automatic collection off
    (after one drain pass) for the duration, restored on exit."""
    was_enabled = gc.isenabled()
    gc.collect()
    gc.disable()
    try:
        yield
    finally:
        if was_enabled:
            gc.enable()
