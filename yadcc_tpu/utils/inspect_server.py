"""Loopback-friendly HTTP introspection endpoint shared by all servers.

Parity with the reference's /inspect/vars JSON dumps on every process
(yadcc/doc/debugging.md:26-174), gated by optional basic auth
(yadcc/common/inspect_auth.h)."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..common.inspect_auth import InspectAuth
from . import exposed_vars


class _Handler(BaseHTTPRequestHandler):
    auth: InspectAuth = InspectAuth("")

    def log_message(self, *args):  # silence per-request stderr noise
        pass

    def do_GET(self):
        if not self.path.startswith("/inspect/vars"):
            self.send_error(404)
            return
        if not self.auth.check(self.headers.get("Authorization")):
            self.send_response(401)
            self.send_header("WWW-Authenticate", 'Basic realm="inspect"')
            self.end_headers()
            return
        prefix = self.path[len("/inspect/vars"):].strip("/")
        body = exposed_vars.dump_json(prefix).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class InspectServer:
    def __init__(self, port: int = 0, credential: str = "",
                 host: str = "127.0.0.1", frontend: str = "threaded"):
        self._auth = InspectAuth(credential)
        if frontend == "aio":
            # Event-loop front end (--rpc-frontend aio): /inspect rides
            # the same loop discipline as the serving path; a dump is
            # quick but may call arbitrary exposed callables, so it
            # runs on the bounded pool, not the loop.
            from ..rpc.aio_server import AioHttpServer

            self._httpd = None
            self._aio = AioHttpServer(self._handle_aio,
                                      address=f"{host}:{port}")
            self.port = self._aio.port
        else:
            self._aio = None
            handler = type("BoundHandler", (_Handler,),
                           {"auth": self._auth})
            self._httpd = ThreadingHTTPServer((host, port), handler)
            self.port = self._httpd.server_port
        self._thread: Optional[threading.Thread] = None

    def _handle_aio(self, responder) -> None:
        if responder.method != "GET" or \
                not responder.path.startswith("/inspect/vars"):
            responder._reply(404, content_type="text/plain")
            return
        if not self._auth.check(responder.headers.get("authorization")):
            responder._reply(401, content_type="text/plain")
            return
        prefix = responder.path[len("/inspect/vars"):].strip("/")

        def dump() -> None:
            responder._reply(200, exposed_vars.dump_json(prefix).encode())

        self._aio.submit(dump)

    def start(self) -> None:
        if self._aio is not None:
            return
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="inspect", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._aio is not None:
            self._aio.stop()
            return
        self._httpd.shutdown()
        self._httpd.server_close()
