"""Lock-order tracing: the race-detection test tier.

The reference runs every test under gperftools *strict* heap checking
(BLADE_ROOT:25-33) and keeps concurrency honest by convention
(`Unsafe*` naming for lock-held methods, documented lock ordering,
task_dispatcher.h:226-268).  CPython has no TSan, so this module makes
the lock-ordering convention *checkable*: while installed, every
`threading.Lock()` / `threading.RLock()` the framework constructs is
wrapped in a traced proxy, and every acquisition records an edge from
each lock the acquiring thread already holds to the new one.  A cycle
in that order graph is a potential-deadlock (ABBA) pattern even if the
interleaving never actually deadlocked during the run — the same
happens-before generalization TSan's lock-order checker uses.

Usage (tests — see tests/test_locktrace.py):

    with locktrace.installed() as graph:
        ... construct components, hammer them from threads ...
    assert graph.violations == []

Production opt-in (mirrors heap_check being baked into the reference's
test config): set YTPU_LOCKTRACE=1 before starting any entry point and
violations are logged once to stderr; `inspect()` surfaces them.

Scope notes:
- Installation swaps the *factories* on the `threading` module, so only
  locks constructed while installed are traced; locks created by other
  libraries during that window are traced too, which is harmless (they
  simply add nodes) but keeps the window small in tests.
- `threading.Condition` works with traced locks: it duck-types on
  acquire/release and falls back to `acquire(0)`-probing for
  `_is_owned`, both of which the proxy provides.
- Overhead is one dict update per acquire on a per-thread structure and
  one bounded graph probe per *new* edge, so stress tests stay fast.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

_real_lock = threading.Lock
_real_rlock = threading.RLock


class LockGraph:
    """Directed lock-order graph with immediate cycle detection."""

    def __init__(self) -> None:
        self._g = _real_lock()  # guards the graph itself (never traced)
        self._edges: Dict[str, Set[str]] = {}
        self._edge_sites: Dict[Tuple[str, str], str] = {}
        self.violations: List[str] = []
        self._reported: Set[Tuple[str, ...]] = set()
        self._tls = threading.local()

    # -- per-thread held stack ------------------------------------------

    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    # -- events ----------------------------------------------------------

    def note_acquired(self, name: str, site: str) -> None:
        held = self._held()
        if held:
            with self._g:
                for prev in held:
                    if prev == name:   # RLock re-entry: no new edge
                        continue
                    succ = self._edges.setdefault(prev, set())
                    if name not in succ:
                        succ.add(name)
                        self._edge_sites[(prev, name)] = site
                        cycle = self._find_cycle_locked(name, prev)
                        if cycle is not None:
                            self._report_locked(cycle)
        held.append(name)

    def note_released(self, name: str) -> None:
        held = self._held()
        # Remove the most recent matching entry: release order need not
        # be LIFO (that by itself is not a violation).
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    # -- cycle machinery (graph lock held) -------------------------------

    def _find_cycle_locked(self, src: str, dst: str
                           ) -> Optional[List[str]]:
        """Path src->...->dst would close a cycle with the new dst->src
        edge; returns the node list if one exists."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _report_locked(self, cycle: List[str]) -> None:
        key = tuple(sorted(cycle))
        if key in self._reported:
            return
        self._reported.add(key)
        hops = []
        for a, b in zip(cycle, cycle[1:] + cycle[:1]):
            site = self._edge_sites.get((a, b), "?")
            hops.append(f"{a} -> {b} (at {site})")
        self.violations.append(
            "lock-order cycle: " + "; ".join(hops))

    def inspect(self) -> dict:
        with self._g:
            return {
                "locks": sorted(
                    set(self._edges) | {b for s in self._edges.values()
                                        for b in s}),
                "edges": sum(len(s) for s in self._edges.values()),
                "violations": list(self.violations),
            }


class _TracedLock:
    """Proxy satisfying the Lock/RLock duck type, reporting to a graph."""

    def __init__(self, graph: LockGraph, name: str, rlock: bool):
        self._inner = _real_rlock() if rlock else _real_lock()
        self._graph = graph
        self._name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            site = _caller_site()
            self._graph.note_acquired(self._name, site)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._graph.note_released(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # threading.Condition probes these when present (RLock only).
    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _acquire_restore(self, state):
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        self._graph.note_acquired(self._name, "condition-reacquire")

    def _release_save(self):
        inner = self._inner
        if hasattr(inner, "_release_save"):
            state = inner._release_save()
        else:
            inner.release()
            state = None
        self._graph.note_released(self._name)
        return state

    def __repr__(self):
        return f"<TracedLock {self._name} {self._inner!r}>"


def _caller_site(depth: int = 2) -> str:
    f = sys._getframe(depth)
    # Walk out of this module's own frames (acquire/__enter__).
    while f and f.f_globals.get("__name__") == __name__:
        f = f.f_back
    if not f:
        return "?"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


_serial = [0]


def _name_from_site() -> str:
    """Name a lock by construction site + per-instance serial: the site
    makes violation reports self-describing, the serial keeps distinct
    locks distinct nodes (two locks born on one line — e.g. striped or
    comprehension-built — must not collapse into a single node, which
    would both hide real inter-instance cycles and mislabel them as
    re-entry)."""
    f = sys._getframe(2)
    while f and f.f_globals.get("__name__") in (__name__, "threading"):
        f = f.f_back
    _serial[0] += 1
    if not f:
        return f"anonymous#{_serial[0]}"
    mod = f.f_globals.get("__name__", "?")
    return f"{mod}:{f.f_lineno}#{_serial[0]}"


_active: Optional[LockGraph] = None


def install() -> LockGraph:
    """Swap threading.Lock/RLock for traced factories. Returns the graph."""
    global _active
    if _active is not None:
        return _active
    graph = LockGraph()
    _active = graph

    def make_lock():
        return _TracedLock(graph, _name_from_site(), rlock=False)

    def make_rlock():
        return _TracedLock(graph, _name_from_site(), rlock=True)

    threading.Lock = make_lock          # type: ignore[misc]
    threading.RLock = make_rlock        # type: ignore[misc]
    return graph


def uninstall() -> None:
    global _active
    threading.Lock = _real_lock         # type: ignore[misc]
    threading.RLock = _real_rlock       # type: ignore[misc]
    _active = None


def active_graph() -> Optional[LockGraph]:
    return _active


@contextlib.contextmanager
def installed():
    graph = install()
    try:
        yield graph
    finally:
        uninstall()


def install_from_env() -> Optional[LockGraph]:
    """Entry-point hook: YTPU_LOCKTRACE=1 turns tracing on for the whole
    process and registers an atexit report (the production analogue of
    the reference's always-on strict heap check in tests)."""
    if not os.environ.get("YTPU_LOCKTRACE"):
        return None
    graph = install()

    from . import exposed_vars

    exposed_vars.expose("yadcc/locktrace", graph.inspect)

    import atexit

    def report():
        if graph.violations:
            sys.stderr.write(
                "locktrace: %d violation(s):\n  %s\n"
                % (len(graph.violations),
                   "\n  ".join(graph.violations)))

    atexit.register(report)
    return graph
