"""Lock-order tracing: the race-detection test tier.

The reference runs every test under gperftools *strict* heap checking
(BLADE_ROOT:25-33) and keeps concurrency honest by convention
(`Unsafe*` naming for lock-held methods, documented lock ordering,
task_dispatcher.h:226-268).  CPython has no TSan, so this module makes
the lock-ordering convention *checkable*: while installed, every
`threading.Lock()` / `threading.RLock()` the framework constructs is
wrapped in a traced proxy, and every acquisition records an edge from
each lock the acquiring thread already holds to the new one.  A cycle
in that order graph is a potential-deadlock (ABBA) pattern even if the
interleaving never actually deadlocked during the run — the same
happens-before generalization TSan's lock-order checker uses.

Usage (tests — see tests/test_locktrace.py):

    with locktrace.installed() as graph:
        ... construct components, hammer them from threads ...
    assert graph.violations == []

Production opt-in (mirrors heap_check being baked into the reference's
test config): set YTPU_LOCKTRACE=1 before starting any entry point and
violations are logged once to stderr; `inspect()` surfaces them.

Scope notes:
- Installation swaps the *factories* on the `threading` module, so only
  locks constructed while installed are traced; locks created by other
  libraries during that window are traced too, which is harmless (they
  simply add nodes) but keeps the window small in tests.
- `threading.Condition` works with traced locks: it duck-types on
  acquire/release and falls back to `acquire(0)`-probing for
  `_is_owned`, both of which the proxy provides.
- Overhead is one dict update per acquire on a per-thread structure and
  one bounded graph probe per *new* edge, so stress tests stay fast.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

_real_lock = threading.Lock
_real_rlock = threading.RLock


class LockGraph:
    """Directed lock-order graph with immediate cycle detection.

    All state (including per-thread held stacks) lives under one
    internal real lock: held stacks are keyed by thread id rather than
    thread-local so a handoff-style release from a *different* thread
    (legal for threading.Lock, used by stdlib internals) can repair the
    acquirer's stack instead of leaving a phantom entry that would
    manufacture false cycles.  Growth is bounded: a proxy's GC prunes
    its node, and a hard edge cap saturates the graph (reported in
    inspect()) rather than letting the cycle probe degrade forever in
    a long-lived traced process."""

    MAX_EDGES = 100_000

    def __init__(self) -> None:
        import collections

        self._g = _real_lock()  # guards the graph itself (never traced)
        self._edges: Dict[str, Set[str]] = {}  # guarded by: self._g
        # Reverse index: O(degree) pruning of GC'd nodes.
        self._preds: Dict[str, Set[str]] = {}  # guarded by: self._g
        self._edge_sites: Dict[Tuple[str, str], str] = \
            {}  # guarded by: self._g
        self.violations: List[str] = []  # guarded by: self._g
        self._reported: Set[Tuple[str, ...]] = set()  # guarded by: self._g
        self._stacks: Dict[int, List[str]] = {}  # guarded by: self._g
        self._n_edges = 0  # guarded by: self._g
        self.saturated = False  # guarded by: self._g
        # GC'd proxies queue their names here (deque.append is atomic,
        # so __del__ — which can fire mid-note_acquired via GC — never
        # touches _g); pruning happens at the next traced event.
        self._dead = collections.deque()

    # -- events ----------------------------------------------------------

    def note_acquired(self, name: str, site: str) -> None:
        tid = threading.get_ident()
        with self._g:
            while self._dead:
                self._forget_locked(self._dead.popleft())
            held = self._stacks.setdefault(tid, [])
            if name in held:
                # RLock re-entry: re-acquiring an owned lock can never
                # deadlock, so it adds NO ordering constraint — not
                # even from other locks acquired in between (recording
                # held->name here would turn the legal pattern
                # `with r: with a: with r:` into a bogus cycle).
                held.append(name)
                return
            for prev in held:
                succ = self._edges.setdefault(prev, set())
                if name not in succ:
                    if self._n_edges >= self.MAX_EDGES:
                        self.saturated = True
                        continue
                    succ.add(name)
                    self._preds.setdefault(name, set()).add(prev)
                    self._n_edges += 1
                    self._edge_sites[(prev, name)] = site
                    cycle = self._find_cycle_locked(name, prev)
                    if cycle is not None:
                        self._report_locked(cycle)
            held.append(name)

    def note_released(self, name: str, owner_tid: Optional[int] = None
                      ) -> None:
        """`owner_tid`: the thread that ACQUIRED the lock (the proxy
        remembers it) — a Lock may legally be released by any thread,
        and the stack to repair is the acquirer's."""
        tid = owner_tid if owner_tid is not None else threading.get_ident()
        with self._g:
            held = self._stacks.get(tid)
            if not held:
                return
            # Remove the most recent matching entry: release order need
            # not be LIFO (that by itself is not a violation).
            for i in range(len(held) - 1, -1, -1):
                if held[i] == name:
                    del held[i]
                    break
            if not held:
                del self._stacks[tid]

    def forget_later(self, name: str) -> None:
        """GC hook: NO locking here — __del__ may run at any allocation
        point, including while this thread already holds _g."""
        self._dead.append(name)

    def forget(self, name: str) -> None:
        with self._g:
            self._forget_locked(name)

    def _forget_locked(self, name: str) -> None:
        """Prune a garbage-collected lock's node (bounded growth for
        per-connection / per-task locks in long-lived processes).
        Already-reported violations keep their rendered strings."""
        out = self._edges.pop(name, None)
        if out:
            self._n_edges -= len(out)
            for b in out:
                self._edge_sites.pop((name, b), None)
                preds_b = self._preds.get(b)
                if preds_b is not None:
                    preds_b.discard(name)
        for a in self._preds.pop(name, ()):
            succ = self._edges.get(a)
            if succ is not None and name in succ:
                succ.discard(name)
                self._n_edges -= 1
                self._edge_sites.pop((a, name), None)

    # -- cycle machinery (graph lock held) -------------------------------

    def _find_cycle_locked(self, src: str, dst: str
                           ) -> Optional[List[str]]:
        """Path src->...->dst would close a cycle with the new dst->src
        edge; returns the node list if one exists."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _report_locked(self, cycle: List[str]) -> None:
        key = tuple(sorted(cycle))
        if key in self._reported:
            return
        self._reported.add(key)
        hops = []
        for a, b in zip(cycle, cycle[1:] + cycle[:1]):
            site = self._edge_sites.get((a, b), "?")
            hops.append(f"{a} -> {b} (at {site})")
        self.violations.append(
            "lock-order cycle: " + "; ".join(hops))

    def inspect(self) -> dict:
        with self._g:
            return {
                "locks": sorted(
                    set(self._edges) | {b for s in self._edges.values()
                                        for b in s}),
                "edges": sum(len(s) for s in self._edges.values()),
                "saturated": self.saturated,
                "violations": list(self.violations),
            }


class _TracedLock:
    """Proxy satisfying the Lock/RLock duck type.

    The reporting graph is resolved PER EVENT from the active layer
    (not captured at construction): a lock born inside a scoped
    installed() window but outliving it must report to the ambient
    layer afterwards, or its orderings silently vanish from the
    operator's process-wide tracing.  Each acquisition remembers which
    graph recorded it (LIFO per lock) so the matching release repairs
    the right graph even across an install/uninstall boundary."""

    def __init__(self, name: str, rlock: bool):
        self._inner = _real_rlock() if rlock else _real_lock()
        self._name = name
        self._rlock = rlock
        self._owner_tid: Optional[int] = None
        self._graph_stack: List[Optional[LockGraph]] = []
        self._seen: Set[LockGraph] = set()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            g = _active
            self._owner_tid = threading.get_ident()
            if g is not None:
                g.note_acquired(self._name, _caller_site())
                self._seen.add(g)
            self._graph_stack.append(g)
        return ok

    def release(self) -> None:
        # For a plain Lock the releasing thread may differ from the
        # acquirer (handoff pattern); the stack to repair is the
        # ACQUIRER's.  RLocks are owner-released by definition.
        owner = threading.get_ident() if self._rlock else self._owner_tid
        self._inner.release()
        g = self._graph_stack.pop() if self._graph_stack else None
        if g is not None:
            g.note_released(self._name, owner)

    def __del__(self):
        try:
            for g in self._seen:
                g.forget_later(self._name)
        except Exception:
            pass

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # threading.Condition probes these when present (RLock only).
    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def _acquire_restore(self, state):
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        # Ownership moves to the woken waiter: a later release must
        # repair THIS thread's stack, not the last plain-acquire()
        # caller's.
        self._owner_tid = threading.get_ident()
        g = _active
        if g is not None:
            g.note_acquired(self._name, "condition-reacquire")
            self._seen.add(g)
        self._graph_stack.append(g)

    def _release_save(self):
        inner = self._inner
        if hasattr(inner, "_release_save"):
            state = inner._release_save()
        else:
            inner.release()
            state = None
        g = self._graph_stack.pop() if self._graph_stack else None
        if g is not None:
            g.note_released(self._name)
        return state

    def __repr__(self):
        return f"<TracedLock {self._name} {self._inner!r}>"


def _caller_site(depth: int = 2) -> str:
    f = sys._getframe(depth)
    # Walk out of this module's own frames (acquire/__enter__).
    while f and f.f_globals.get("__name__") == __name__:
        f = f.f_back
    if not f:
        return "?"
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


import itertools

_serial = itertools.count(1)  # next() is atomic in CPython — two locks
#                               born concurrently on one line must not
#                               share a name (a shared name collapses
#                               distinct instances into one node and
#                               real inter-instance cycles read as
#                               re-entry).


def _name_from_site() -> str:
    """Name a lock by construction site + per-instance serial: the site
    makes violation reports self-describing, the serial keeps distinct
    locks distinct nodes."""
    f = sys._getframe(2)
    while f and f.f_globals.get("__name__") in (__name__, "threading"):
        f = f.f_back
    n = next(_serial)
    if not f:
        return f"anonymous#{n}"
    mod = f.f_globals.get("__name__", "?")
    return f"{mod}:{f.f_lineno}#{n}"


_active: Optional[LockGraph] = None


def install() -> LockGraph:
    """Swap threading.Lock/RLock for traced factories bound to a FRESH
    graph; returns it.  Installation nests: each install() stacks over
    whatever was active (ambient YTPU_LOCKTRACE tracing included), and
    uninstall() restores the previous layer — so a scoped `installed()`
    block inside a traced process neither inherits stale edges nor
    permanently disables the operator's process-wide tracing."""
    global _active
    graph = LockGraph()
    graph._prev = (_active, threading.Lock, threading.RLock)
    _active = graph

    def make_lock():
        return _TracedLock(_name_from_site(), rlock=False)

    def make_rlock():
        return _TracedLock(_name_from_site(), rlock=True)

    threading.Lock = make_lock          # type: ignore[misc]
    threading.RLock = make_rlock        # type: ignore[misc]
    return graph


def uninstall() -> None:
    """Pop the most recent install(), restoring the previous layer."""
    global _active
    if _active is None:
        threading.Lock = _real_lock     # type: ignore[misc]
        threading.RLock = _real_rlock   # type: ignore[misc]
        return
    prev_active, prev_lock, prev_rlock = _active._prev
    threading.Lock = prev_lock          # type: ignore[misc]
    threading.RLock = prev_rlock        # type: ignore[misc]
    _active = prev_active


def active_graph() -> Optional[LockGraph]:
    return _active


@contextlib.contextmanager
def installed():
    graph = install()
    try:
        yield graph
    finally:
        uninstall()


def framework_violations(graph: LockGraph,
                         needle: str = "yadcc_tpu") -> List[str]:
    """Violations involving at least one framework-constructed lock.

    Lock names carry their construction module (`_name_from_site`), so
    filtering on the package name separates OUR ordering bugs from
    cycles purely among third-party locks (tracing a window in which
    jax compiles will wrap jax's internal locks too — their internal
    ordering is not this repo's CI gate).  Used by the tier-1 stress
    fixtures (tests/test_stress.py, tests/test_pipelined_dispatch.py),
    which run under tracing unconditionally and assert this is empty.
    """
    return [v for v in graph.violations if needle in v]


def install_from_env() -> Optional[LockGraph]:
    """Entry-point hook: YTPU_LOCKTRACE=1 turns tracing on for the whole
    process and registers an atexit report (the production analogue of
    the reference's always-on strict heap check in tests)."""
    if not os.environ.get("YTPU_LOCKTRACE"):
        return None
    graph = install()

    from . import exposed_vars

    exposed_vars.expose("yadcc/locktrace", graph.inspect)

    import atexit

    def report():
        if graph.violations:
            sys.stderr.write(
                "locktrace: %d violation(s):\n  %s\n"
                % (len(graph.violations),
                   "\n  ".join(graph.violations)))

    atexit.register(report)
    return graph
