"""Per-stage latency reservoirs for the grant path.

The full-RPC-path artifact (artifacts/pod_sim_50k.json) showed a
grant_call_p99 of 11.58ms with no way to tell WHERE the time went —
dispatch kernel, lock waits, serialization, or thread handoffs.  Every
stage of the grant path (queue-wait → snapshot → policy → apply →
serialize → transport) records into one of these; `percentiles()` is
the `latency_breakdown` section of pod_sim artifacts and /inspect.

Time sources are injectable: components that already take a Clock
(TaskDispatcher) time their stages with it, so the accounting is
testable with VirtualClock — see tests/test_latency_breakdown.py.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional

import numpy as np

# The serving front end's transport stages (rpc/aio_server.py records
# them; pod_sim surfaces them as `latency_breakdown.frontend_stages`):
# `accept` = connection open -> first complete request, `read` = first
# byte of a request -> the byte completing it, `parse` = incremental
# decode CPU, `write` = response gather-write to the transport.  With
# these, the residual grant_call time that used to lump into
# "queue-wait/transport" is attributable stage by stage
# (doc/scheduler.md "Grant-path stage budget").
FRONTEND_STAGES = ("accept", "read", "parse", "write")


class _Reservoir:
    """Fixed-size ring of the most recent samples plus a total count."""

    __slots__ = ("buf", "n", "count", "total")

    def __init__(self, maxlen: int):
        self.buf = np.empty(maxlen, np.float64)
        self.n = 0          # filled entries (<= maxlen)
        self.count = 0      # lifetime samples (ring write cursor source)
        self.total = 0.0

    def add(self, seconds: float) -> None:
        self.buf[self.count % len(self.buf)] = seconds
        self.count += 1
        self.n = min(self.n + 1, len(self.buf))
        self.total += seconds


class StageTimer:
    """Thread-safe named-stage latency recorder.

    Stages are created on first record; `record()` is O(1) (one ring
    write under a short lock) so it is safe on the dispatch hot path.
    """

    def __init__(self, stages: Iterable[str] = (), maxlen: int = 4096):
        self._maxlen = maxlen
        self._lock = threading.Lock()
        self._stages: Dict[str, _Reservoir] = {
            s: _Reservoir(maxlen) for s in stages
        }  # guarded by: self._lock

    def record(self, stage: str, seconds: float) -> None:
        with self._lock:
            r = self._stages.get(stage)
            if r is None:
                r = self._stages[stage] = _Reservoir(self._maxlen)
            r.add(seconds)

    def reset(self) -> None:
        with self._lock:
            for r in self._stages.values():
                r.n = r.count = 0
                r.total = 0.0

    def stages(self) -> list:
        """Stage names that have recorded at least one sample."""
        with self._lock:
            return [s for s, r in self._stages.items() if r.n > 0]

    def stage_count(self, stage: str) -> int:
        """Lifetime sample count for one stage (0 when unknown)."""
        with self._lock:
            r = self._stages.get(stage)
            return 0 if r is None else r.count

    def stage_samples(self, stage: str) -> Optional[np.ndarray]:
        """The retained samples for one stage (seconds), oldest-first
        not guaranteed; None when the stage never recorded."""
        with self._lock:
            r = self._stages.get(stage)
            if r is None or r.n == 0:
                return None
            return r.buf[: r.n].copy()

    def percentiles(self) -> Dict[str, Dict[str, float]]:
        """{stage: {count, mean_ms, p50_ms, p99_ms}} over the retained
        window (the last `maxlen` samples per stage)."""
        with self._lock:
            snap = [(name, r.buf[: r.n].copy(), r.count, r.total)
                    for name, r in self._stages.items() if r.n > 0]
        out: Dict[str, Dict[str, float]] = {}
        for name, samples, count, total in snap:
            p50, p99 = np.percentile(samples * 1000.0, (50, 99))
            out[name] = {
                "count": int(count),
                "mean_ms": round(float(total / count) * 1000.0, 4),
                "p50_ms": round(float(p50), 4),
                "p99_ms": round(float(p99), 4),
            }
        return out
