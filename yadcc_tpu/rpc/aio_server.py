"""Async event-loop RPC/HTTP front end.

The reference rides flare's M:N fiber runtime so tens of thousands of
delegates can hold long-poll waits without burning a thread stack each;
our serving layer was thread-per-connection (``ThreadingHTTPServer``,
grpc's ``ThreadPoolExecutor``).  This module rebuilds the serving path
on ONE selector event loop (asyncio) while keeping the wire *frame*
format byte-identical (transport.py: ``[u32 status][u32 meta_len][meta]
[attachment]``):

* :class:`AioRpcServer` — hosts the same ``ServiceSpec`` objects the
  grpc transport mounts, over a raw-TCP length-prefixed envelope.
  Frames are parsed incrementally from non-blocking sockets
  (:class:`FrameStreamParser` — partial reads, pipelining and
  slow-loris byte-drip are all just states of the parser), handlers run
  unmodified on a BOUNDED worker pool, and responses gather-write their
  PR-4 ``Payload`` segments straight to the transport (no join).
* *Parked* methods (``ServiceSpec.add_parked``): long-poll handlers
  that would otherwise park a worker thread instead take a ``done``
  continuation.  A waiting client then costs a pending-table entry and
  a loop timer — not an 8MB thread stack and two condvar handoffs.
  The completing thread (e.g. the scheduler's dispatch thread) calls
  ``done(...)`` directly and the loop writes the bytes.
* :class:`AioChannel` — the matching sync client (``aio://host:port``),
  one persistent connection per target with seq-matched pipelining, so
  grant-keeper dry polls stop reconnecting per poll.
  :class:`AsyncAioChannel` is the loop-native client used by simulators
  to hold thousands of concurrent calls on a handful of threads.
* :class:`AioHttpServer` — a minimal HTTP/1.1 server with the same
  responder surface as ``BaseHTTPRequestHandler`` subset the daemon's
  routes use (``_reply``), keep-alive by default, long-polls parked via
  the same continuation discipline.

Stage accounting: the servers record ``accept`` / ``read`` / ``parse``
/ ``write`` into a ``utils.stagetimer.StageTimer`` so the residual
transport time in grant_call decompositions is attributable
(doc/scheduler.md "Grant-path stage budget").

Scope discipline (enforced by ``ytpu-analyze``'s ``aio-blocking``
rule): coroutines in this package must never make blocking calls —
sleep, file/socket I/O, or sync RPC ``.call`` — or the loop silently
regresses to the thread-per-connection latency profile it replaces.
"""

from __future__ import annotations

import asyncio
import itertools
import struct
import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import looplag
from ..utils.logging import get_logger
from ..utils.stagetimer import FRONTEND_STAGES, StageTimer
from .transport import (
    Channel,
    Payload,
    RpcContext,
    RpcError,
    ServiceSpec,
    STATUS_METHOD_NOT_FOUND,
    STATUS_TIMEOUT,
    STATUS_TRANSPORT_FAILURE,
    apply_faults,
    decode_frame_views,
    dispatch_frame_payload,
    encode_frame,
    encode_frame_payload,
)

logger = get_logger("rpc.aio")

# Envelope framing over the TCP stream.  Both directions:
#
#     [u32 len][u32 seq][payload bytes...]      (len counts seq+payload)
#
# Request payload:  [u16 svc_len][u16 method_len][svc][method][frame]
# Response payload: [frame]
#
# The *frame* bytes are byte-identical to what the grpc transport
# carries for the same call — that is the wire-parity claim the
# dataplane-corpus smoke proves (tools/rpc_frontend_bench.py).
_ENVELOPE = struct.Struct("<II")
_REQ_PREAMBLE = struct.Struct("<HH")
_MAX_ENVELOPE = (1 << 30) + 64  # grpc _MAX_MESSAGE parity + preamble


class ProtocolError(Exception):
    """Unrecoverable stream corruption; the connection must close."""


class FrameStreamParser:
    """Incremental envelope parser for the raw-TCP frame transport.

    ``feed(data)`` returns every complete ``(seq, payload)`` message the
    stream holds so far — zero on a partial read, many on a pipelined
    burst; a slow-loris byte-drip simply keeps returning [].  Oversized
    or nonsense lengths raise :class:`ProtocolError` (the stream cannot
    be resynchronized).
    """

    __slots__ = ("_buf", "_need", "_seq")

    def __init__(self):
        self._buf = bytearray()
        self._need = -1  # payload bytes still unknown
        self._seq = 0

    def feed(self, data) -> List[Tuple[int, bytes]]:
        self._buf += data
        out: List[Tuple[int, bytes]] = []
        while True:
            if self._need < 0:
                if len(self._buf) < _ENVELOPE.size:
                    break
                length, seq = _ENVELOPE.unpack_from(self._buf)
                if length < 4 or length > _MAX_ENVELOPE:
                    raise ProtocolError(f"bad envelope length {length}")
                self._need = length - 4  # seq already consumed
                self._seq = seq
                del self._buf[:_ENVELOPE.size]
            if len(self._buf) < self._need:
                break
            payload = bytes(self._buf[: self._need])
            del self._buf[: self._need]
            self._need = -1
            out.append((self._seq, payload))
        return out

    def pending_bytes(self) -> int:
        return len(self._buf)


def split_request_payload(payload) -> Tuple[str, str, memoryview]:
    """Request payload -> (service, method, frame_view)."""
    if len(payload) < _REQ_PREAMBLE.size:
        raise ProtocolError("truncated request preamble")
    svc_len, m_len = _REQ_PREAMBLE.unpack_from(payload)
    off = _REQ_PREAMBLE.size
    if off + svc_len + m_len > len(payload):
        raise ProtocolError("request preamble overruns payload")
    mv = memoryview(payload)
    service = bytes(mv[off:off + svc_len]).decode("utf-8", "replace")
    method = bytes(
        mv[off + svc_len:off + svc_len + m_len]).decode("utf-8", "replace")
    return service, method, mv[off + svc_len + m_len:]


def make_request_payload(service: str, method: str, frame) -> List[bytes]:
    svc = service.encode()
    m = method.encode()
    return [_REQ_PREAMBLE.pack(len(svc), len(m)), svc, m, frame]


def _envelope_segments(seq: int, payload_segments: List[bytes]) -> List:
    total = 4 + sum(len(s) for s in payload_segments)
    return [_ENVELOPE.pack(total, seq)] + payload_segments


# ---------------------------------------------------------------------------
# The event loop host.
# ---------------------------------------------------------------------------


# Cadence of the always-on per-loop liveness tick (lag_s below).  One
# timer per loop at 4Hz — cheap enough to leave on in production, which
# is the point: looplag.installed() only watches during tests, while a
# stalled accept loop must be visible on /inspect/vars in the field.
_TICK_INTERVAL_S = 0.25


class EventLoopThread:
    """One asyncio loop on one daemon thread, shared by any number of
    servers.  ``--rpc-frontend aio`` processes run one of these per
    accept loop (N with SO_REUSEPORT — see AioServerGroup);
    tests create and dispose of them freely."""

    def __init__(self, name: str = "aio-loop"):
        self.name = name
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True)
        self._started = threading.Event()
        self._last_tick = _time.monotonic()
        self._thread.start()
        self._started.wait(5.0)
        looplag.register(self.loop, name)
        try:
            self.loop.call_soon_threadsafe(self._tick)
        except RuntimeError:
            pass  # loop already closed (teardown race in tests)

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.call_soon(self._started.set)
        self.loop.run_forever()

    # ytpu: loop-only
    def _tick(self) -> None:
        self._last_tick = _time.monotonic()
        if not self.loop.is_closed():
            self.loop.call_later(_TICK_INTERVAL_S, self._tick)  # ytpu: allow(async-timer-leak)  # self-rearming liveness tick: it dies with the loop, there is never anything to cancel

    def lag_s(self) -> float:
        """Seconds the loop is overdue for its liveness tick; ~0.0 on a
        healthy loop, grows while a handler stalls it."""
        return max(0.0,
                   _time.monotonic() - self._last_tick - _TICK_INTERVAL_S)

    def run_sync(self, coro, timeout: float = 10.0):
        """Run a coroutine on the loop from a foreign thread, blocking
        for its result (setup/teardown plumbing, never the data path)."""
        return asyncio.run_coroutine_threadsafe(
            coro, self.loop).result(timeout)

    def call_soon(self, fn, *args) -> None:
        self.loop.call_soon_threadsafe(fn, *args)

    def stop(self) -> None:
        if self.loop.is_closed():
            return

        def _halt():
            self.loop.stop()

        self.loop.call_soon_threadsafe(_halt)
        self._thread.join(timeout=5.0)
        if not self.loop.is_running():
            self.loop.close()


class LoopTimer:
    """Thread-safe cancel handle for a ``call_later`` armed from any
    thread.  The loop's own TimerHandle only exists after the
    call_soon_threadsafe hop lands; ``cancel()`` before the hop
    suppresses arming, ``cancel()`` after it cancels on the loop.
    Either way the timer dies — a parked continuation that wins the
    race against its deadline MUST cancel, or the deadline fires into
    the (runtime-guarded) settled responder and the handle pins the
    closure until the deadline elapses."""

    __slots__ = ("_loops", "_lock", "_handle", "_cancelled")

    def __init__(self, loops: EventLoopThread):
        self._loops = loops
        self._lock = threading.Lock()
        self._handle = None
        self._cancelled = False

    # ytpu: loop-only
    def _arm(self, delay_s: float, fn, args) -> None:
        with self._lock:
            if self._cancelled:
                return
            self._handle = self._loops.loop.call_later(
                delay_s, fn, *args)

    def cancel(self) -> None:
        with self._lock:
            self._cancelled = True
            handle, self._handle = self._handle, None
        if handle is not None:
            # TimerHandle.cancel is not thread-safe; hop to the loop.
            # A loop already stopped (teardown racing a completion
            # continuation) has no timers left to fire — nothing to do.
            try:
                self._loops.call_soon(handle.cancel)
            except RuntimeError:
                pass

    @property
    def cancelled(self) -> bool:
        with self._lock:
            return self._cancelled


# ---------------------------------------------------------------------------
# RPC server.
# ---------------------------------------------------------------------------


class _RpcConnection(asyncio.Protocol):
    __slots__ = ("server", "parser", "transport", "peer",
                 "_accepted_at", "_first_request_seen",
                 "_read_started_at")

    def __init__(self, server: "AioRpcServer"):
        self.server = server
        self.parser = FrameStreamParser()
        self.transport: Optional[asyncio.Transport] = None
        self.peer = ""
        self._accepted_at = _time.perf_counter()
        self._first_request_seen = False
        self._read_started_at: Optional[float] = None

    def connection_made(self, transport) -> None:
        self.transport = transport
        peername = transport.get_extra_info("peername") or ("?", 0)
        self.peer = f"{peername[0]}:{peername[1]}"
        self.server._conn_opened(self)

    def connection_lost(self, exc) -> None:
        self.server._conn_closed(self)

    def data_received(self, data) -> None:  # ytpu: loop-only
        timer = self.server.stage_timer
        now = _time.perf_counter()
        if self._read_started_at is None:
            self._read_started_at = now
        try:
            t0 = _time.perf_counter()
            messages = self.parser.feed(data)
            timer.record("parse", _time.perf_counter() - t0)
        except ProtocolError as e:
            logger.warning("rpc stream error from %s: %s", self.peer, e)
            self.transport.close()
            return
        if not messages:
            return
        # A request's `read` stage: first byte of its envelope to the
        # byte that completed it (pipelined requests completing in one
        # chunk share the chunk's read span).
        timer.record("read", now - self._read_started_at)
        self._read_started_at = (
            None if self.parser.pending_bytes() == 0 else now)
        if not self._first_request_seen:
            self._first_request_seen = True
            timer.record("accept", now - self._accepted_at)
        for seq, payload in messages:
            self.server._dispatch(self, seq, payload)

    # -- writes (loop thread only) -----------------------------------------

    # ytpu: loop-only
    def send_payload(self, seq: int, payload: Payload) -> None:
        if self.transport is None or self.transport.is_closing():
            return
        t0 = _time.perf_counter()
        segments = list(payload.iter_segments())
        self.transport.writelines(_envelope_segments(seq, segments))
        self.server.stage_timer.record("write", _time.perf_counter() - t0)


class AioRpcServer:
    """Hosts ServiceSpecs on a TCP port via one event loop.

    Sync handlers run on a bounded ``ThreadPoolExecutor`` (default 8 —
    handlers are short; long-polls belong in parked methods).  Methods
    registered via ``ServiceSpec.add_parked`` run ON the loop with a
    ``done`` continuation and MUST NOT block (ytpu-analyze
    ``aio-blocking`` enforces this package-wide).
    """

    def __init__(self, address: str = "127.0.0.1:0", *,
                 loops: Optional[EventLoopThread] = None,
                 max_workers: int = 8,
                 reuse_port: bool = False):
        self._services: Dict[str, ServiceSpec] = {}
        self._own_loops = loops is None
        self.loops = loops or EventLoopThread(name="aio-rpc")
        self.stage_timer = StageTimer(FRONTEND_STAGES, maxlen=16384)
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="aio-rpc-worker")
        self._conns: set = set()
        self._conn_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._double_replies = 0
        host, _, port = address.rpartition(":")
        self._asyncio_server = self.loops.run_sync(
            self._start_server(host or "127.0.0.1", int(port),
                               reuse_port))
        self.port = self._asyncio_server.sockets[0].getsockname()[1]

    async def _start_server(self, host, port, reuse_port):
        return await self.loops.loop.create_server(
            lambda: _RpcConnection(self), host, port,
            reuse_port=reuse_port or None, backlog=1024)

    def add_service(self, spec: ServiceSpec) -> None:
        self._services[spec.service_name] = spec

    def start(self) -> None:
        pass  # serving from construction; kept for GrpcServer parity

    def stop(self, grace: Optional[float] = 1.0) -> None:
        async def _close():
            self._asyncio_server.close()
            await self._asyncio_server.wait_closed()
            with self._conn_lock:
                conns = list(self._conns)
            for c in conns:
                if c.transport is not None:
                    c.transport.close()

        try:
            self.loops.run_sync(_close())
        except Exception:
            pass
        self._pool.shutdown(wait=False)
        if self._own_loops:
            self.loops.stop()

    # -- connection registry -------------------------------------------------

    def _conn_opened(self, conn) -> None:
        with self._conn_lock:
            self._conns.add(conn)

    def _conn_closed(self, conn) -> None:
        with self._conn_lock:
            self._conns.discard(conn)

    def connection_count(self) -> int:
        with self._conn_lock:
            return len(self._conns)

    def _note_double_reply(self) -> None:
        with self._stats_lock:
            self._double_replies += 1

    def inspect(self) -> Dict[str, int]:
        """Runtime complement to the static reply-once rule: every
        refused second reply is counted here, so a protocol defect that
        slips past analysis still shows up in /inspect surfaces."""
        with self._stats_lock:
            doubles = self._double_replies
        return {"connections": self.connection_count(),
                "double_replies": doubles, "port": self.port,
                "loop_lag_s": round(self.loops.lag_s(), 4)}

    # -- dispatch (loop thread) ----------------------------------------------

    # ytpu: loop-only
    def _dispatch(self, conn: _RpcConnection, seq: int, payload) -> None:
        try:
            service, method, frame = split_request_payload(payload)
        except ProtocolError as e:
            logger.warning("rpc preamble error from %s: %s", conn.peer, e)
            conn.transport.close()
            return
        spec = self._services.get(service)
        if spec is None:
            conn.send_payload(seq, encode_frame_payload(
                STATUS_METHOD_NOT_FOUND,
                f"no service {service}".encode()))
            return
        parked = spec.parked.get(method)
        if parked is not None:
            self._dispatch_parked(conn, seq, spec, parked, frame)
            return
        loop = self.loops.loop
        fut = loop.run_in_executor(
            self._pool, dispatch_frame_payload, spec, method, frame,
            conn.peer)
        fut.add_done_callback(
            lambda f: self._send_result(conn, seq, f))

    # ytpu: loop-only
    def _send_result(self, conn, seq, fut) -> None:
        try:
            reply = fut.result()
        except Exception as e:  # handler pool died; keep the connection
            logger.exception("aio dispatch failed")
            reply = encode_frame_payload(
                STATUS_TRANSPORT_FAILURE, f"dispatch error: {e!r}".encode())
        conn.send_payload(seq, reply)

    # ytpu: loop-only
    def _dispatch_parked(self, conn, seq, spec: ServiceSpec, ms,
                         frame) -> None:
        """Long-poll path: the handler runs on the loop, registers its
        continuation with the owning component and returns without a
        response.  The completing thread calls ``done`` which encodes
        and writes from the loop.  The parked client's cost: this
        closure + whatever pending-table entry the component keeps."""
        timer = spec.stage_timer
        t0 = _time.perf_counter()
        try:
            _, meta, attachment = decode_frame_views(frame)
            req = ms.request_cls.FromString(meta)
        except Exception as e:
            conn.send_payload(seq, encode_frame_payload(
                STATUS_TRANSPORT_FAILURE,
                f"malformed request: {e!r}".encode()))
            return
        ctx = RpcContext(peer=conn.peer)
        fired = [False]
        fired_lock = threading.Lock()

        def done(resp, *, error: Optional[RpcError] = None) -> None:
            with fired_lock:
                if fired[0]:
                    self._note_double_reply()
                    return
                fired[0] = True
            t1 = _time.perf_counter()
            if error is not None:
                reply = encode_frame_payload(error.status,
                                             error.message.encode())
            else:
                reply = encode_frame_payload(
                    0, resp.SerializeToString(), ctx.response_attachment)
            if timer is not None:
                timer.record(f"{ms.name}:handler", t1 - t0)
                timer.record(f"{ms.name}:serialize",
                             _time.perf_counter() - t1)
            self.loops.call_soon(conn.send_payload, seq, reply)

        try:
            ms.handler(req, attachment, ctx, done)
        except RpcError as e:
            done(None, error=e)
        except Exception as e:
            logger.exception("parked handler %s failed", ms.name)
            done(None, error=RpcError(STATUS_TRANSPORT_FAILURE,
                                      f"handler error: {e!r}"))

    def call_later(self, delay_s: float, fn, *args) -> LoopTimer:
        """Schedule ``fn`` on the loop — the timer half of a parked
        continuation (deadline replies, poll re-arms).  Returns a
        thread-safe handle; the continuation that beats its deadline
        must ``cancel()`` it (async-timer-leak discipline)."""
        timer = LoopTimer(self.loops)
        self.loops.call_soon(timer._arm, delay_s, fn, args)
        return timer


class AioServerGroup:
    """N accept loops on ONE port: each loop owns a full ``AioRpcServer``
    bound with ``SO_REUSEPORT``, so the kernel shards incoming
    connections across loops and every connection's parser, parked
    continuations and deadline timers live on the loop that accepted it
    — no cross-loop state, no shared accept lock.

    Mirrors the shard router's aggregation contract: ``inspect()``
    returns the sum of the per-loop counters plus a ``per_loop`` list,
    and the sum must equal what a single-loop server would report for
    the same workload (tested).  The group quacks like ``AioRpcServer``
    (``port`` / ``add_service`` / ``start`` / ``stop`` / ``call_later``
    / ``connection_count`` / ``inspect``) so entries and ``LocalCluster``
    swap it in via ``make_rpc_server(..., accept_loops=N)``.
    """

    def __init__(self, address: str = "127.0.0.1:0", *,
                 accept_loops: int = 2, max_workers: int = 8):
        if accept_loops < 1:
            raise ValueError(f"accept_loops must be >= 1, "
                             f"got {accept_loops}")
        self.accept_loops = accept_loops
        # The pool exists only for non-parked methods; split it so the
        # group's total worker count matches a single-loop server's.
        per_workers = max(1, max_workers // accept_loops)
        host, _, port = address.rpartition(":")
        host = host or "127.0.0.1"
        self._loops: List[EventLoopThread] = []
        self._servers: List[AioRpcServer] = []
        bind_port = int(port)
        for i in range(accept_loops):
            loops = EventLoopThread(name=f"aio-rpc-{i}")
            server = AioRpcServer(f"{host}:{bind_port}", loops=loops,
                                  max_workers=per_workers,
                                  reuse_port=True)
            # Loop 0 resolves ":0"; the rest must land on the same port
            # for SO_REUSEPORT to shard instead of scatter.
            bind_port = server.port
            self._loops.append(loops)
            self._servers.append(server)
        self.port = self._servers[0].port
        self.stage_timer = self._servers[0].stage_timer
        self._rr = itertools.count()

    def add_service(self, spec: ServiceSpec) -> None:
        # One ServiceSpec shared by all loops: specs are read-only after
        # registration and handlers hand thread-safety to the owning
        # component, exactly as with a single server.
        for server in self._servers:
            server.add_service(spec)

    def start(self) -> None:
        pass  # serving from construction; GrpcServer parity

    def stop(self, grace: Optional[float] = 1.0) -> None:
        for server in self._servers:
            server.stop(grace)
        # The servers were handed their loops, so they did not stop
        # them (_own_loops is False); the group owns loop lifetime.
        for loops in self._loops:
            loops.stop()

    def call_later(self, delay_s: float, fn, *args) -> LoopTimer:
        """Timer for component-side deadlines that are not tied to a
        connection (connection-bound timers arm on the dispatching
        server's own loop).  Round-robins across loops so a timer storm
        does not pile onto loop 0."""
        server = self._servers[next(self._rr) % len(self._servers)]
        return server.call_later(delay_s, fn, *args)

    def connection_count(self) -> int:
        return sum(s.connection_count() for s in self._servers)

    def inspect(self) -> Dict[str, object]:
        per_loop = []
        for i, server in enumerate(self._servers):
            entry = dict(server.inspect())
            entry["loop"] = f"aio-rpc-{i}"
            per_loop.append(entry)
        return {
            "connections": sum(e["connections"] for e in per_loop),
            "double_replies": sum(e["double_replies"] for e in per_loop),
            "port": self.port,
            "accept_loops": self.accept_loops,
            "per_loop": per_loop,
        }


# ---------------------------------------------------------------------------
# Clients.
# ---------------------------------------------------------------------------

# Process-wide connection accounting for the keep-alive claim: dials is
# sockets actually connected, reuses is calls served on an existing
# connection (the dry-poll fix in ISSUE 10's satellite is visible as
# reuses >> dials).
_conn_stats_lock = threading.Lock()
_conn_stats = {"dials": 0, "reuses": 0}


def _note_dial() -> None:
    with _conn_stats_lock:
        _conn_stats["dials"] += 1


def _note_reuse() -> None:
    with _conn_stats_lock:
        _conn_stats["reuses"] += 1


def aio_connection_stats() -> Dict[str, int]:
    with _conn_stats_lock:
        return dict(_conn_stats)


class _SyncReader(threading.Thread):
    """Reader side of AioChannel's persistent socket: demuxes pipelined
    responses to per-seq waiters."""

    def __init__(self, channel: "AioChannel", sock):
        super().__init__(name="aio-chan-reader", daemon=True)
        self.channel = channel
        self.sock = sock

    def run(self) -> None:
        parser = FrameStreamParser()
        try:
            while True:
                data = self.sock.recv(1 << 16)
                if not data:
                    break
                for seq, payload in parser.feed(data):
                    self.channel._complete(seq, payload)
        except (OSError, ProtocolError):
            pass
        self.channel._reader_died(self)


class AioChannel(Channel):
    """Sync client channel for ``aio://host:port``.

    One persistent connection per channel; concurrent callers pipeline
    over it with seq matching (the reader thread demuxes).  Dials are
    counted once per socket, so long-poll loops that used to reconnect
    per poll now show up as one dial and N reuses
    (``aio_connection_stats``)."""

    def __init__(self, uri: str):
        target = uri[len("aio://"):] if uri.startswith("aio://") else uri
        self._target = target
        host, _, port = target.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self._lock = threading.Lock()
        self._sock = None  # guarded by: self._lock
        self._reader: Optional[_SyncReader] = None  # guarded by: self._lock
        self._next_seq = 1  # guarded by: self._lock
        self._waiters: Dict[int, list] = {}  # guarded by: self._lock

    # -- connection lifecycle ------------------------------------------------

    def _ensure_sock(self):
        import socket as _socket

        with self._lock:
            if self._sock is not None:
                _note_reuse()
                return self._sock
        sock = _socket.create_connection(self._addr, timeout=10.0)
        sock.settimeout(None)
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        with self._lock:
            if self._sock is not None:  # raced; keep the winner
                sock.close()
                _note_reuse()
                return self._sock
            self._sock = sock
            self._reader = _SyncReader(self, sock)
            self._reader.start()
        _note_dial()
        return sock

    def _complete(self, seq: int, payload: bytes) -> None:
        with self._lock:
            waiter = self._waiters.pop(seq, None)
        if waiter is not None:
            waiter[1] = payload
            waiter[0].set()

    def _reader_died(self, reader) -> None:
        with self._lock:
            if self._reader is not reader:
                return  # an old generation; the live socket is fine
            sock, self._sock, self._reader = self._sock, None, None
            waiters, self._waiters = self._waiters, {}
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        for waiter in waiters.values():
            waiter[0].set()  # payload stays None -> transport failure

    # -- the call ------------------------------------------------------------

    def call(self, service, method_name, request, response_cls,
             attachment=b"", timeout=None):
        apply_faults(self._target, service, method_name)
        frame = encode_frame(0, request.SerializeToString(), attachment)
        try:
            sock = self._ensure_sock()
        except OSError as e:
            raise RpcError(STATUS_TRANSPORT_FAILURE,
                           f"connect {self._target}: {e}") from e
        event = threading.Event()
        waiter = [event, None]
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._waiters[seq] = waiter
        data = b"".join(_envelope_segments(
            seq, make_request_payload(service, method_name, frame)))
        try:
            with self._lock:
                live = self._sock
            if live is not sock or live is None:
                raise OSError("connection replaced")
            sock.sendall(data)
        except OSError as e:
            with self._lock:
                self._waiters.pop(seq, None)
            self._teardown()
            raise RpcError(STATUS_TRANSPORT_FAILURE,
                           f"send {self._target}: {e}") from e
        if not event.wait(timeout if timeout is not None else 300.0):
            with self._lock:
                self._waiters.pop(seq, None)
            raise RpcError(STATUS_TIMEOUT,
                           f"timed out waiting on {self._target}")
        if waiter[1] is None:
            raise RpcError(STATUS_TRANSPORT_FAILURE,
                           f"connection to {self._target} lost")
        status, meta, att = decode_frame_views(waiter[1])
        if status != 0:
            raise RpcError(status, bytes(meta).decode(errors="replace"))
        return response_cls.FromString(meta), att

    def call_raw(self, service, method_name, frame: bytes,
                 timeout: Optional[float] = None) -> bytes:
        """Send a pre-encoded request frame, return the raw reply frame
        (byte-parity harness; production uses call())."""
        apply_faults(self._target, service, method_name)
        sock = self._ensure_sock()
        event = threading.Event()
        waiter = [event, None]
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._waiters[seq] = waiter
        sock.sendall(b"".join(_envelope_segments(
            seq, make_request_payload(service, method_name, frame))))
        if not event.wait(timeout if timeout is not None else 30.0) or \
                waiter[1] is None:
            raise RpcError(STATUS_TRANSPORT_FAILURE, "raw call failed")
        return waiter[1]

    def _teardown(self) -> None:
        with self._lock:
            sock, self._sock, self._reader = self._sock, None, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        self._teardown()


class AsyncAioChannel:
    """Loop-native client: thousands of concurrent calls on one
    connection, each an awaiting coroutine instead of a parked thread.
    Construct and use from ON the loop."""

    def __init__(self, target: str):
        target = target[len("aio://"):] if target.startswith("aio://") \
            else target
        self._target = target
        host, _, port = target.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self._transport = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._next_seq = 1
        self._parser = FrameStreamParser()
        self._conn_lock: Optional[asyncio.Lock] = None

    async def connect(self) -> None:
        loop = asyncio.get_running_loop()
        chan = self

        class _Proto(asyncio.Protocol):
            def data_received(self, data):
                for seq, payload in chan._parser.feed(data):
                    fut = chan._pending.pop(seq, None)
                    if fut is not None and not fut.done():
                        fut.set_result(payload)

            def connection_lost(self, exc):
                chan._fail_all()

        self._transport, _ = await loop.create_connection(
            _Proto, *self._addr)
        _note_dial()

    def _fail_all(self) -> None:
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(RpcError(
                    STATUS_TRANSPORT_FAILURE, "connection lost"))

    async def call(self, service, method_name, request, response_cls,
                   attachment=b"", timeout: Optional[float] = None):
        # Same chaos seam as every sync channel (tools/scenarios.py).
        # An injector that sleeps stalls the loop — scenario injectors
        # targeting the aio path raise or use sub-ms delays.
        apply_faults(self._target, service, method_name)
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:  # concurrent callers dial once
            if self._transport is None or self._transport.is_closing():
                await self.connect()
            else:
                _note_reuse()
        frame = encode_frame(0, request.SerializeToString(), attachment)
        seq = self._next_seq
        self._next_seq += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[seq] = fut
        self._transport.writelines(_envelope_segments(
            seq, make_request_payload(service, method_name, frame)))
        try:
            payload = await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._pending.pop(seq, None)
            raise RpcError(STATUS_TIMEOUT, "call timed out") from None
        status, meta, att = decode_frame_views(payload)
        if status != 0:
            raise RpcError(status, bytes(meta).decode(errors="replace"))
        return response_cls.FromString(meta), att

    def close(self) -> None:
        if self._transport is not None:
            self._transport.close()
            self._transport = None


# ---------------------------------------------------------------------------
# HTTP/1.1 server.
# ---------------------------------------------------------------------------

_HTTP_STATUS_TEXT = {
    200: "OK", 400: "Bad Request", 401: "Unauthorized", 404: "Not Found",
    413: "Request Entity Too Large", 500: "Internal Server Error",
    501: "Not Implemented", 503: "Service Unavailable",
}
_MAX_HEADER_BYTES = 64 << 10


class HttpRequest:
    __slots__ = ("method", "path", "version", "headers", "body")

    def __init__(self, method, path, version, headers, body):
        self.method = method
        self.path = path
        self.version = version
        self.headers = headers  # dict, lower-cased keys
        self.body = body


class HttpStreamParser:
    """Incremental HTTP/1.1 request parser (Content-Length bodies only —
    every client of the daemon's loopback API sends one; chunked TE is
    refused upstream with 501).  Tolerates the same adversarial streams
    as the frame parser: partial reads, pipelining, byte-drip."""

    __slots__ = ("_buf", "_headers_done", "_req", "_body_need", "_cap")

    def __init__(self, max_body: int):
        self._buf = bytearray()
        self._headers_done = False
        self._req: Optional[HttpRequest] = None
        self._body_need = 0
        self._cap = max_body

    def feed(self, data) -> List[HttpRequest]:
        self._buf += data
        out: List[HttpRequest] = []
        while True:
            if not self._headers_done:
                end = self._buf.find(b"\r\n\r\n")
                if end < 0:
                    if len(self._buf) > _MAX_HEADER_BYTES:
                        raise ProtocolError("oversized header block")
                    break
                head = bytes(self._buf[:end]).decode("latin-1")
                del self._buf[:end + 4]
                lines = head.split("\r\n")
                parts = lines[0].split()
                if len(parts) != 3:
                    raise ProtocolError(f"bad request line {lines[0]!r}")
                headers: Dict[str, str] = {}
                for line in lines[1:]:
                    k, _, v = line.partition(":")
                    headers[k.strip().lower()] = v.strip()
                if "transfer-encoding" in headers:
                    raise ProtocolError("chunked bodies unsupported")
                try:
                    need = int(headers.get("content-length", 0) or 0)
                except ValueError:
                    raise ProtocolError("bad content-length")
                if need < 0 or need > self._cap:
                    # Surfaced as a 413 by the server, not a hard close:
                    # the cap is policy, not stream corruption.
                    raise BodyOverCap(parts[0], parts[1], headers)
                self._req = HttpRequest(parts[0], parts[1], parts[2],
                                        headers, b"")
                self._body_need = need
                self._headers_done = True
            if len(self._buf) < self._body_need:
                break
            req = self._req
            req.body = bytes(self._buf[: self._body_need])
            del self._buf[: self._body_need]
            self._headers_done = False
            self._req = None
            self._body_need = 0
            out.append(req)
        return out


class BodyOverCap(Exception):
    """Content-Length over the wire cap: reply 413, keep parsing is
    impossible (the body bytes would follow) so the connection closes
    after the reply."""

    def __init__(self, method, path, headers):
        super().__init__("body over cap")
        self.method = method
        self.path = path
        self.headers = headers


class AioHttpResponder:
    """The reply surface handlers get — duck-type compatible with the
    ``_reply`` subset of the threaded BaseHTTPRequestHandler routes.
    ``_reply`` is once-only and thread-safe: a parked long-poll's
    completion and its deadline timer may race, the first wins."""

    __slots__ = ("server", "_conn", "request", "method", "path",
                 "headers", "_reply_lock", "_replied")

    def __init__(self, server: "AioHttpServer", conn: "_HttpConnection",
                 request: HttpRequest):
        self.server = server
        self._conn = conn
        self.request = request
        self.method = request.method
        self.path = request.path
        self.headers = request.headers
        self._reply_lock = threading.Lock()
        self._replied = False

    def release_request(self) -> None:
        """Drop the request body/headers before parking: an idle
        long-poll client should cost its continuation, not its whole
        parsed request (the ISSUE-10 parked-memory budget)."""
        self.request = None
        self.headers = None

    @property
    def replied(self) -> bool:
        with self._reply_lock:
            return self._replied

    def _reply(self, code: int, body=b"",
               content_type: str = "application/json",
               retry_after_s: Optional[float] = None) -> bool:
        """Returns True iff THIS call produced the response — parked
        completions and deadline timers race, and cleanup that must
        happen exactly once (e.g. free_task) belongs to the winner."""
        with self._reply_lock:
            if self._replied:
                self.server._note_double_reply()
                return False
            self._replied = True
        head = [f"HTTP/1.1 {code} {_HTTP_STATUS_TEXT.get(code, 'X')}",
                f"Content-Type: {content_type}",
                f"Content-Length: {len(body)}"]
        if retry_after_s is not None:
            head.append(f"Retry-After: {retry_after_s:g}")
        head.append("\r\n")
        header_bytes = "\r\n".join(head).encode("latin-1")
        segments = [header_bytes]
        if isinstance(body, Payload):
            segments.extend(body.iter_segments())
        elif body:
            segments.append(body)
        self.server.loops.call_soon(self._conn.write_segments, segments)
        return True


class _HttpConnection(asyncio.Protocol):
    __slots__ = ("server", "parser", "transport", "peer",
                 "_accepted_at", "_first_seen")

    def __init__(self, server: "AioHttpServer"):
        self.server = server
        self.parser = HttpStreamParser(server.max_body)
        self.transport = None
        self.peer = ""
        self._accepted_at = _time.perf_counter()
        self._first_seen = False

    def connection_made(self, transport) -> None:
        self.transport = transport
        peername = transport.get_extra_info("peername") or ("?", 0)
        self.peer = f"{peername[0]}:{peername[1]}"
        self.server._conn_opened(self)

    def connection_lost(self, exc) -> None:
        self.server._conn_closed(self)

    def data_received(self, data) -> None:  # ytpu: loop-only
        timer = self.server.stage_timer
        try:
            t0 = _time.perf_counter()
            requests = self.parser.feed(data)
            timer.record("parse", _time.perf_counter() - t0)
        except BodyOverCap:
            body = self.server.too_large_body
            self.transport.write(
                (f"HTTP/1.1 413 Request Entity Too Large\r\n"
                 f"Content-Type: application/json\r\n"
                 f"Content-Length: {len(body)}\r\n"
                 f"Connection: close\r\n\r\n").encode("latin-1") + body)
            self.transport.close()
            return
        except ProtocolError as e:
            logger.warning("http stream error from %s: %s", self.peer, e)
            self.transport.close()
            return
        if requests and not self._first_seen:
            self._first_seen = True
            timer.record("accept", _time.perf_counter() - self._accepted_at)
        for req in requests:
            self._invoke_handler(AioHttpResponder(self.server, self, req))

    # ytpu: loop-only
    def _invoke_handler(self, responder) -> None:  # ytpu: responder(responder)
        try:
            self.server.handler_fn(responder)
        except Exception:
            logger.exception("http handler failed for %s", responder.path)
            # A handler that already replied and THEN raised must not
            # double-fire the 500 into the settled stream.
            if not responder.replied:
                responder._reply(500)

    # ytpu: loop-only
    def write_segments(self, segments) -> None:
        if self.transport is None or self.transport.is_closing():
            return
        t0 = _time.perf_counter()
        self.transport.writelines(segments)
        self.server.stage_timer.record(
            "write", _time.perf_counter() - t0)


class AioHttpServer:
    """Event-loop HTTP/1.1 front end.

    ``handler_fn(responder)`` runs on the loop for every request: it
    must either reply, park (register a continuation + deadline timer),
    or hand blocking work to ``submit()``'s bounded pool.  Keep-alive
    is the default (HTTP/1.1); an idle parked client costs its
    responder + timer, nothing else."""

    def __init__(self, handler_fn: Callable[[AioHttpResponder], None],
                 address: str = "127.0.0.1:0", *,
                 loops: Optional[EventLoopThread] = None,
                 max_workers: int = 8,
                 max_body: int = 1 << 30,
                 too_large_body: bytes = b'{"error":"body too large"}'):
        self.handler_fn = handler_fn
        self.max_body = max_body
        self.too_large_body = too_large_body
        self._own_loops = loops is None
        self.loops = loops or EventLoopThread(name="aio-http")
        self.stage_timer = StageTimer(FRONTEND_STAGES, maxlen=16384)
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="aio-http-worker")
        self._conns: set = set()
        self._conn_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._double_replies = 0
        host, _, port = address.rpartition(":")
        self._asyncio_server = self.loops.run_sync(
            self._start(host or "127.0.0.1", int(port)))
        self.port = self._asyncio_server.sockets[0].getsockname()[1]

    async def _start(self, host, port):
        return await self.loops.loop.create_server(
            lambda: _HttpConnection(self), host, port, backlog=1024)

    def submit(self, fn, *args) -> None:
        """Run blocking route work on the bounded pool."""
        self._pool.submit(self._guard, fn, *args)

    @staticmethod
    def _guard(fn, *args) -> None:
        try:
            fn(*args)
        except Exception:
            logger.exception("http pool task failed")

    def call_later(self, delay_s: float, fn, *args) -> LoopTimer:
        """See AioRpcServer.call_later: returns a thread-safe cancel
        handle so the winning continuation can kill its deadline."""
        timer = LoopTimer(self.loops)
        self.loops.call_soon(timer._arm, delay_s, fn, args)
        return timer

    def connection_count(self) -> int:
        with self._conn_lock:
            return len(self._conns)

    def _conn_opened(self, conn) -> None:
        with self._conn_lock:
            self._conns.add(conn)

    def _conn_closed(self, conn) -> None:
        with self._conn_lock:
            self._conns.discard(conn)

    def _note_double_reply(self) -> None:
        with self._stats_lock:
            self._double_replies += 1

    def inspect(self) -> Dict[str, int]:
        """Refused second replies, for the same reason as
        AioRpcServer.inspect: the runtime half of reply-once."""
        with self._stats_lock:
            doubles = self._double_replies
        return {"connections": self.connection_count(),
                "double_replies": doubles, "port": self.port,
                "loop_lag_s": round(self.loops.lag_s(), 4)}

    def start(self) -> None:
        pass

    def stop(self) -> None:
        async def _close():
            self._asyncio_server.close()
            await self._asyncio_server.wait_closed()
            with self._conn_lock:
                conns = list(self._conns)
            for c in conns:
                if c.transport is not None:
                    c.transport.close()

        try:
            self.loops.run_sync(_close())
        except Exception:
            pass
        self._pool.shutdown(wait=False)
        if self._own_loops:
            self.loops.stop()
