"""Transport layer for the control plane.

The reference runs on the flare RPC framework (protobuf services with
first-class *attachments* so bulk bytes skip message serialization, and
``mock://`` channels for in-process service fakes in tests — reference
yadcc/daemon/local/distributed_task_dispatcher_test.cc:33-35).  This
framework keeps both ideas with two interchangeable transports:

* ``grpc://host:port`` — production transport over grpc's generic (bytes
  in / bytes out) API, with a tiny length-prefixed frame carrying the
  serialized message plus an optional attachment.
* ``mock://name`` — a process-local registry of servers, used by every
  unit test to fake the scheduler / cache / peer-servant services without
  sockets.

Services are plain objects exposing ``service_name`` and a ``methods``
table; the same object can be mounted on either transport.
"""

from .transport import (
    Channel,
    FailoverChannel,
    RpcContext,
    RpcError,
    ServiceSpec,
    install_fault_injector,
    method,
    register_mock_server,
    retry_after_ms_from_error,
    unregister_mock_server,
)
from .grpc_transport import GrpcServer

__all__ = [
    "Channel",
    "FailoverChannel",
    "GrpcServer",
    "RpcContext",
    "RpcError",
    "ServiceSpec",
    "install_fault_injector",
    "method",
    "register_mock_server",
    "retry_after_ms_from_error",
    "unregister_mock_server",
]


def make_rpc_server(frontend: str, address: str, *, max_workers: int = 32,
                    accept_loops: int = 1):
    """Factory for the `--rpc-frontend aio|threaded` flag: "threaded" is
    the grpc thread-pool server (the long-standing default, kept
    verbatim as the A/B + fallback), "aio" the event-loop front end
    (rpc/aio_server.py, doc/scheduler.md "RPC front end").

    ``accept_loops`` > 1 shards the aio accept path across N
    SO_REUSEPORT event loops (AioServerGroup); the threaded front end
    ignores it — its pool is the concurrency knob."""
    if frontend == "aio":
        if accept_loops > 1:
            from .aio_server import AioServerGroup

            return AioServerGroup(address, accept_loops=accept_loops,
                                  max_workers=max_workers)
        from .aio_server import AioRpcServer

        return AioRpcServer(address, max_workers=max_workers)
    if frontend in ("threaded", "grpc"):
        return GrpcServer(address, max_workers=max_workers)
    raise ValueError(f"unknown rpc frontend {frontend!r}")
