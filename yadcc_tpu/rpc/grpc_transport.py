"""grpc-backed transport.

Uses grpc's generic (bytes-in/bytes-out) handler API so no grpc_tools
codegen is required: every method is a unary-unary call on the path
``/<service_name>/<method>`` whose payload is the frame defined in
transport.py.  Attachments therefore never pass through protobuf
serialization, mirroring the reference's flare attachments.

Connection pools are deliberately tiny (one channel per target): the
reference keeps 2 connections per server to dodge TCP idle slow-start
(yadcc/daemon/entry.cc:88-98); HTTP/2 multiplexing gives us the same
property with one.
"""

from __future__ import annotations

import threading
from concurrent import futures
from typing import Dict, Optional, Tuple

import grpc

from .transport import (
    Channel,
    RpcError,
    ServiceSpec,
    STATUS_TIMEOUT,
    STATUS_TRANSPORT_FAILURE,
    apply_faults,
    decode_frame_views,
    dispatch_frame,
    encode_frame,
)

_MAX_MESSAGE = 1 << 30  # 1 GiB, matches the reference's largest packet cap.

_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", _MAX_MESSAGE),
    ("grpc.max_receive_message_length", _MAX_MESSAGE),
]


def _peer_to_hostport(peer: str) -> str:
    # grpc peers look like "ipv4:1.2.3.4:56" or "ipv6:[::1]:56".
    if peer.startswith("ipv4:"):
        return peer[5:]
    if peer.startswith("ipv6:"):
        return peer[5:]
    return peer


class _GenericService(grpc.GenericRpcHandler):
    def __init__(self, services: Dict[str, ServiceSpec]):
        self._services = services

    def service(self, handler_call_details):
        # Path: /<service>/<method>
        _, service, method_name = handler_call_details.method.split("/", 2)
        spec = self._services.get(service)
        if spec is None:
            return None

        def unary(request: bytes, context) -> bytes:
            return dispatch_frame(
                spec, method_name, request,
                peer=_peer_to_hostport(context.peer()))

        return grpc.unary_unary_rpc_method_handler(
            unary,
            request_deserializer=None,  # raw bytes
            response_serializer=None,
        )


class GrpcServer:
    """Hosts ServiceSpecs on a TCP port."""

    def __init__(self, address: str = "0.0.0.0:0", max_workers: int = 32):
        self._services: Dict[str, ServiceSpec] = {}
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=_CHANNEL_OPTIONS,
        )
        self._server.add_generic_rpc_handlers(
            (_GenericService(self._services),))
        self.port = self._server.add_insecure_port(address)
        if self.port == 0:
            raise RuntimeError(f"cannot bind {address}")

    def add_service(self, spec: ServiceSpec) -> None:
        self._services[spec.service_name] = spec

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: Optional[float] = 1.0) -> None:
        self._server.stop(grace).wait()


class GrpcChannel(Channel):
    def __init__(self, uri: str):
        target = uri[len("grpc://") :] if uri.startswith("grpc://") else uri
        self._target = target
        self._channel = grpc.insecure_channel(target, options=_CHANNEL_OPTIONS)
        self._lock = threading.Lock()
        self._callables: Dict[Tuple[str, str], grpc.UnaryUnaryMultiCallable] \
            = {}  # guarded by: self._lock

    def _callable(self, service: str, method_name: str):
        key = (service, method_name)
        with self._lock:
            c = self._callables.get(key)
            if c is None:
                c = self._channel.unary_unary(
                    f"/{service}/{method_name}",
                    request_serializer=None,
                    response_deserializer=None,
                )
                self._callables[key] = c
        return c

    def call(self, service, method_name, request, response_cls,
             attachment=b"", timeout=None):
        # Scenario fault seam (tools/scenarios.py): may sleep (WAN
        # latency/jitter) or raise RpcError (flaky peer).  A no-op
        # global read unless a simulation installed an injector.
        apply_faults(self._target, service, method_name)
        # The socket boundary: encode_frame flattens header + meta +
        # attachment segments exactly once (a Payload attachment arrives
        # here never having been copied).
        frame = encode_frame(0, request.SerializeToString(), attachment)
        try:
            reply = self._callable(service, method_name)(frame, timeout=timeout)
        except grpc.RpcError as e:  # transport-level failure
            code = e.code() if hasattr(e, "code") else None
            status = (STATUS_TIMEOUT
                      if code == grpc.StatusCode.DEADLINE_EXCEEDED
                      else STATUS_TRANSPORT_FAILURE)
            raise RpcError(status, str(code)) from e
        status, meta, att = decode_frame_views(reply)
        if status != 0:
            raise RpcError(status, bytes(meta).decode(errors="replace"))
        return response_cls.FromString(meta), att

    def call_raw(self, service, method_name, frame: bytes,
                 timeout: Optional[float] = None) -> bytes:
        """Send a pre-encoded request frame, return the raw reply frame
        (byte-parity harness for the aio front end; production uses
        call())."""
        return self._callable(service, method_name)(frame, timeout=timeout)

    def close(self) -> None:
        self._channel.close()
