"""Transport-agnostic RPC core: frames, service specs, channels.

Wire frame (both directions, same on grpc and raw usage):

    [u32 status][u32 meta_len][meta bytes][attachment bytes...]

``status`` is 0 on success; non-zero values are application status codes
(the per-service ``*_STATUS_*`` enums in yadcc_tpu/api).  Attachments are
whatever bytes follow the message — the transport never copies them into
a protobuf field (reference flare attachments, e.g. yadcc/api/cache.proto
comment on TryGetEntry).
"""

from __future__ import annotations

import struct
import threading
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple, Union

from ..common.payload import Payload, as_payload

_HEADER = struct.Struct("<II")

# Attachments travel as bytes-likes or chunked Payloads; the transport
# flattens them exactly once, at the socket boundary.
Attachment = Union[bytes, bytearray, memoryview, Payload]


class RpcError(Exception):
    """Application-level RPC failure with a numeric status code."""

    def __init__(self, status: int, message: str = ""):
        super().__init__(f"rpc failed: status={status} {message}")
        self.status = status
        self.message = message


# Transport-level status codes (distinct range from app statuses).
STATUS_TRANSPORT_FAILURE = 1
STATUS_METHOD_NOT_FOUND = 2
STATUS_TIMEOUT = 3
# A live endpoint that is deliberately not serving yet — a warm standby
# awaiting takeover (scheduler/replication.py).  The wire's 503: the
# error message carries a machine-readable "retry-after-ms=N" hint
# (parse with retry_after_ms_from_error).  FailoverChannel treats it
# like a dead peer and rotates to the next URI.
STATUS_NOT_SERVING = 4


def retry_after_ms_from_error(err: "RpcError",
                              default_ms: int = 250) -> int:
    """Extract the "retry-after-ms=N" hint a NOT_SERVING standby embeds
    in its error message.  Error frames carry only (status, message),
    so the hint travels in-band."""
    marker = "retry-after-ms="
    msg = err.message or ""
    at = msg.find(marker)
    if at < 0:
        return default_ms
    digits = []
    for ch in msg[at + len(marker):]:
        if not ch.isdigit():
            break
        digits.append(ch)
    return int("".join(digits)) if digits else default_ms


@dataclass
class RpcContext:
    """Per-call server-side context."""

    # Peer address as observed by the transport ("ip:port"), used e.g.
    # for the scheduler's NAT detection (observed vs reported endpoint).
    peer: str = ""
    # Response attachment, set by the handler — bytes or a chunked
    # Payload (flattened once, into the reply frame).
    response_attachment: Attachment = b""


# A handler takes (request_message, request_attachment, context) and
# returns the response message (attachment goes via ctx).
Handler = Callable[[object, bytes, RpcContext], object]

# A parked handler additionally takes a `done` continuation and returns
# nothing: it registers the continuation with the owning component and
# the COMPLETING thread calls done(response) (or done(None, error=
# RpcError(...))) exactly once, from any thread.  Only the aio front
# end (rpc/aio_server.py) consults these; thread-per-request transports
# keep using the blocking twin registered under the same name.
ParkedHandler = Callable[[object, bytes, RpcContext, Callable], None]


@dataclass
class MethodSpec:
    name: str
    request_cls: type
    handler: Handler


@dataclass
class ServiceSpec:
    """A mountable service: name plus method table.

    `stage_timer` (optional, a utils.stagetimer.StageTimer) makes
    dispatch_frame record per-method `<Method>:handler` and
    `<Method>:serialize` stages — the server-side half of the grant
    path's latency decomposition (doc/scheduler.md).

    `parked` maps long-poll methods to their continuation-style
    handlers (see ParkedHandler): on the aio front end a waiting client
    is a parked continuation on the event loop instead of a parked
    worker thread.  Methods without a parked variant run their blocking
    handler on the front end's bounded pool."""

    service_name: str
    methods: Dict[str, MethodSpec] = field(default_factory=dict)
    stage_timer: Optional[object] = None
    parked: Dict[str, MethodSpec] = field(default_factory=dict)

    def add(self, name: str, request_cls: type, handler: Handler) -> None:
        self.methods[name] = MethodSpec(name, request_cls, handler)

    def add_parked(self, name: str, request_cls: type,
                   handler: ParkedHandler) -> None:
        self.parked[name] = MethodSpec(name, request_cls, handler)


def method(spec: ServiceSpec, request_cls: type):
    """Decorator registering a bound method on a ServiceSpec by name."""

    def deco(fn):
        spec.add(fn.__name__, request_cls, fn)
        return fn

    return deco


def encode_frame_payload(status: int, meta: bytes,
                         attachment: Attachment = b"") -> Payload:
    """Gather form of a wire frame: [header+meta] ++ attachment segments.

    The attachment's buffers are referenced, never copied — the single
    flatten happens in the caller's ``join()`` at the socket boundary
    (header and meta are small; packing them into one segment keeps the
    hot no-attachment case a single allocation)."""
    return Payload.of(_HEADER.pack(status, len(meta)) + meta,
                      as_payload(attachment))


def encode_frame(status: int, meta: bytes,
                 attachment: Attachment = b"") -> bytes:
    return encode_frame_payload(status, meta, attachment).join()


def decode_frame_views(data) -> Tuple[int, memoryview, memoryview]:
    """Zero-copy decode: meta and attachment are views into ``data``
    (which they pin alive — for a reply frame that is the buffer the
    transport handed back anyway)."""
    status, meta_len = _HEADER.unpack_from(data)
    off = _HEADER.size
    mv = memoryview(data)
    return status, mv[off:off + meta_len], mv[off + meta_len:]


def decode_frame(data: bytes) -> Tuple[int, bytes, bytes]:
    status, meta_len = _HEADER.unpack_from(data)
    off = _HEADER.size
    return status, data[off : off + meta_len], data[off + meta_len :]


# Per-thread duration of the last dispatch_frame call (decode + handler
# + serialize), in seconds.  An in-process transport (mock://) runs the
# server on the caller's thread, so the client can subtract this from
# its wall time to get the pure transport/framing stage — how pod_sim
# decomposes grant_call latency.
_tls = threading.local()


def last_server_inner_s() -> Optional[float]:
    return getattr(_tls, "server_inner_s", None)


def dispatch_frame_payload(spec: ServiceSpec, name: str, data,
                           peer: str) -> Payload:  # ytpu: untrusted(data)
    """Server-side: decode a request frame, run the handler, encode the
    reply as a gather Payload (the aio front end writes its segments
    straight to the socket; the joined twin below serves byte-oriented
    transports).

    Never raises: malformed frames, undecodable messages and handler
    crashes all turn into status frames, so mock://, grpc:// and aio://
    expose identical failure semantics to callers.
    """
    timer = spec.stage_timer
    t0 = _time.perf_counter()
    ms = spec.methods.get(name)
    if ms is None:
        return encode_frame_payload(STATUS_METHOD_NOT_FOUND, b"")
    try:
        # Views, not slices: a multi-MB source attachment reaches the
        # handler without being copied out of the request frame.
        _, meta, attachment = decode_frame_views(data)
        req = ms.request_cls.FromString(meta)
    except Exception as e:
        return encode_frame_payload(STATUS_TRANSPORT_FAILURE,
                                    f"malformed request: {e!r}".encode())
    ctx = RpcContext(peer=peer)
    try:
        resp = ms.handler(req, attachment, ctx)
    except RpcError as e:
        out = encode_frame_payload(e.status, e.message.encode())
        _tls.server_inner_s = _time.perf_counter() - t0
        return out
    except Exception as e:
        out = encode_frame_payload(STATUS_TRANSPORT_FAILURE,
                                   f"handler error: {e!r}".encode())
        _tls.server_inner_s = _time.perf_counter() - t0
        return out
    t1 = _time.perf_counter()
    out = encode_frame_payload(0, resp.SerializeToString(),
                               ctx.response_attachment)
    t2 = _time.perf_counter()
    if timer is not None:
        # handler covers request decode too (both are message-codec
        # work on the request side; the response side is `serialize`).
        timer.record(f"{name}:handler", t1 - t0)
        timer.record(f"{name}:serialize", t2 - t1)
    _tls.server_inner_s = t2 - t0
    return out


def dispatch_frame(spec: ServiceSpec, name: str, data: bytes, peer: str) -> bytes:  # ytpu: untrusted(data)
    return dispatch_frame_payload(spec, name, data, peer).join()


# --------------------------------------------------------------------------
# Fault-injection seam (tools/scenarios.py).
#
# The hostile-world scenario matrix needs to impose WAN latency/jitter,
# flaky peers, and slow-loris servants on the REAL wire path without
# forking the transports.  One process-global hook, called by every
# Channel.call implementation before the request leaves: it may sleep
# (latency), raise RpcError (drop/refuse), or do nothing.  Production
# never installs one — the None fast path is a single global read.
# --------------------------------------------------------------------------

# fn(target, service, method) -> None; may sleep or raise RpcError.
_fault_injector: Optional[Callable[[str, str, str], None]] = None


def install_fault_injector(
        fn: Optional[Callable[[str, str, str], None]]) -> None:
    """Install (or, with None, clear) the process-wide RPC fault hook.
    ``target`` is the channel's destination ("host:port" or a mock
    name), so an injector can single out one servant."""
    global _fault_injector
    _fault_injector = fn


def apply_faults(target: str, service: str, method_name: str) -> None:
    fn = _fault_injector
    if fn is not None:
        fn(target, service, method_name)


# --------------------------------------------------------------------------
# mock:// transport — in-process server registry for tests.
# --------------------------------------------------------------------------

_mock_servers: Dict[str, Dict[str, ServiceSpec]] = {}
_mock_lock = threading.Lock()


def register_mock_server(name: str, *services: ServiceSpec) -> None:
    with _mock_lock:
        _mock_servers[name] = {s.service_name: s for s in services}


def unregister_mock_server(name: str) -> None:
    with _mock_lock:
        _mock_servers.pop(name, None)


class Channel:
    """Client-side channel; scheme-dispatched factory.

    ``Channel("grpc://10.0.0.1:8336")``, ``Channel("aio://10.0.0.1:8336")``
    (the event-loop front end's raw-TCP frame transport) or
    ``Channel("mock://scheduler")``.  A bare "host:port" is treated as
    grpc.

    A comma-separated URI list ("grpc://a:8336,grpc://b:8336") builds a
    FailoverChannel over the members in order of preference — how
    daemons dial an active scheduler with a warm standby behind it
    (doc/robustness.md, "Warm-standby failover").
    """

    def __new__(cls, uri: str):
        if cls is not Channel:
            return super().__new__(cls)
        # Return the concrete subclass instance; Python's call protocol
        # then runs its __init__ exactly once (do NOT call it here).
        if "," in uri:
            return object.__new__(FailoverChannel)
        if uri.startswith("mock://"):
            return object.__new__(_MockChannel)
        if uri.startswith("aio://"):
            from .aio_server import AioChannel

            return object.__new__(AioChannel)
        from .grpc_transport import GrpcChannel

        return object.__new__(GrpcChannel)

    def call(
        self,
        service: str,
        method_name: str,
        request,
        response_cls: type,
        attachment: bytes = b"",
        timeout: Optional[float] = None,
    ) -> Tuple[object, bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FailoverChannel(Channel):
    """A channel over an ordered URI list ("active,standby,...").

    Calls go to the currently-preferred member; on a transport-shaped
    failure (TRANSPORT_FAILURE, TIMEOUT, NOT_SERVING) the channel
    rotates to the next URI under common/backoff.py pacing and retries,
    up to two laps around the list before surfacing the last error.
    Application-status errors (NO_QUOTA, refusals, ...) pass straight
    through — a different scheduler would answer them the same way.

    Member channels are built lazily and cached, so a standby that was
    never needed is never dialed.  Fault injection stays per-member:
    each underlying channel applies the process-wide injector against
    its own target, exactly as a directly-dialed channel would."""

    # Failures that mean "this endpoint can't serve me right now",
    # as opposed to "my request was ruled on".
    _ROTATE_STATUSES = frozenset(
        (STATUS_TRANSPORT_FAILURE, STATUS_TIMEOUT, STATUS_NOT_SERVING))

    def __init__(self, uri: str):
        self._uris = tuple(u.strip() for u in uri.split(",") if u.strip())
        if len(self._uris) < 2:
            raise ValueError(f"failover channel needs >= 2 URIs: {uri!r}")
        self._lock = threading.Lock()
        self._chans: Dict[int, Channel] = {}  # guarded by: self._lock
        self._preferred = 0  # guarded by: self._lock
        self._failovers = 0  # guarded by: self._lock

    def _member(self, idx: int) -> Channel:
        with self._lock:
            ch = self._chans.get(idx)
            if ch is None:
                ch = Channel(self._uris[idx])
                self._chans[idx] = ch
            return ch

    def call(self, service, method_name, request, response_cls,
             attachment=b"", timeout=None):
        from ..common.backoff import Backoff

        with self._lock:
            start = self._preferred
        backoff = Backoff(initial_s=0.02, max_s=0.5)
        last: Optional[RpcError] = None
        for attempt in range(2 * len(self._uris)):
            idx = (start + attempt) % len(self._uris)
            try:
                result = self._member(idx).call(
                    service, method_name, request, response_cls,
                    attachment, timeout)
            except RpcError as e:
                if e.status not in self._ROTATE_STATUSES:
                    raise
                last = e
                retry_after_s = None
                if e.status == STATUS_NOT_SERVING:
                    retry_after_s = retry_after_ms_from_error(e) / 1000.0
                # Drop the dead member's channel so the next attempt
                # re-dials instead of reusing a wedged connection.
                with self._lock:
                    stale = self._chans.pop(idx, None)
                if stale is not None:
                    try:
                        stale.close()
                    except Exception:
                        pass
                backoff.wait(retry_after_s)
                continue
            with self._lock:
                if self._preferred != idx:
                    self._failovers += 1
                    self._preferred = idx
            return result
        assert last is not None
        raise last

    def preferred_uri(self) -> str:
        with self._lock:
            return self._uris[self._preferred]

    def failovers(self) -> int:
        with self._lock:
            return self._failovers

    def close(self) -> None:
        with self._lock:
            chans, self._chans = list(self._chans.values()), {}
        for ch in chans:
            try:
                ch.close()
            except Exception:
                pass


class _MockChannel(Channel):
    """``mock://name`` — optionally ``mock://name@ip:port`` to control the
    peer address the server-side context observes (exercises NAT
    detection and self-avoidance in tests)."""

    def __init__(self, uri: str):
        rest = uri[len("mock://") :]
        self._name, _, peer = rest.partition("@")
        self._peer = peer or "127.0.0.1:0"

    def call(self, service, method_name, request, response_cls,
             attachment=b"", timeout=None):
        apply_faults(self._name, service, method_name)
        with _mock_lock:
            services = _mock_servers.get(self._name)
        if services is None or service not in services:
            raise RpcError(STATUS_TRANSPORT_FAILURE,
                           f"no mock server for {self._name}/{service}")
        frame = encode_frame(0, request.SerializeToString(), attachment)
        reply = dispatch_frame(services[service], method_name, frame,
                               peer=self._peer)
        status, meta, att = decode_frame_views(reply)
        if status != 0:
            raise RpcError(status, bytes(meta).decode(errors="replace"))
        return response_cls.FromString(meta), att
