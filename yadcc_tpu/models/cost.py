"""Dispatch cost model: the scheduling policy's constants, declaratively.

The reference hard-codes its servant-selection heuristics inside
TaskDispatcher (yadcc/scheduler/task_dispatcher.cc:362-451): never pick
ineligible servants, prefer dedicated servants under 50% load (SMT
heuristic — the second hyperthread of a core contributes far less), avoid
assigning a requestor its own task, and among the rest pick the minimum
running/capacity utilization.  This framework expresses the same policy
as a small set of named constants consumed by both implementations of the
DispatchPolicy SPI — the greedy CPU oracle and the batched device kernel
— so the two can never drift apart silently.
"""

from __future__ import annotations

from dataclasses import dataclass


# Utilization is fixed-point (util_q = running * UTIL_SCALE // capacity):
# float division is backend-dependent at the last ulp (XLA may lower f32
# div to reciprocal-multiply), which broke device-vs-oracle tie-breaking
# on mathematically equal utilizations like 12/28 vs 9/21.  Integer math
# is exact, deterministic everywhere, and cheaper on TPU.  With capacity
# bounded by ~4096 cores, running*65536 stays far inside int32.
UTIL_SCALE = 65536


@dataclass(frozen=True)
class DispatchCostModel:
    # Dedicated servants below this utilization are preferred outright
    # over any non-dedicated servant (reference task_dispatcher.cc:399-410).
    # Fixed-point, UTIL_SCALE denominator (default: 50%).
    dedicated_preference_utilization_q: int = UTIL_SCALE // 2

    # Never hand a requestor its own task: compiling locally through the
    # network path would only add overhead (reference :370-379).
    avoid_self: bool = True

    # Score offset subtracted for preferred-dedicated candidates; larger
    # than any possible utilization (UTIL_SCALE) so the tier ordering is
    # strict.
    preference_bonus_q: int = 4 * UTIL_SCALE

    # Score assigned to non-candidates; dominates every real score.
    infeasible_score_q: int = 1 << 30


DEFAULT_COST_MODEL = DispatchCostModel()
