"""Per-tenant budget ledgers: grants, queued demand, cache bytes.

Budgets answer a different question than fairness.  The two-level
stride queue shares *available* capacity by weight; a budget bounds
what one tenant may *hold* regardless of how idle the rest of the
fleet is — the blast-radius bound that makes a runaway CI loop a
tenant-local incident.  Enforcement points (doc/tenancy.md):

* scheduler grant mint / release  — TenantLedger.charge / release
* scheduler admission (pre-ladder) — TenantLedger.over_budget; an
  over-budget tenant gets a native FLOW_REJECT + retry-after WITHOUT
  touching the ladder, so its refused demand never pushes the global
  signal and cannot starve other tenants into degradation rungs
* cache-entry fill               — CacheBytesLedger.try_charge

All ledgers are leaf locks (nothing is called while they are held).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from yadcc_tpu.tenancy.identity import TenantDirectory, TenantSpec


class TenantOverBudget(Exception):
    """Raised at an enforcement point when admitting one more unit
    would exceed the tenant's budget.  Carries the tenant id and the
    retry hint the transport layer should surface (HTTP 503 +
    Retry-After at the delegate, FLOW_REJECT + retry_after_ms at the
    scheduler)."""

    def __init__(self, tenant: str, retry_after_ms: int = 500):
        super().__init__(f"tenant {tenant!r} over budget")
        self.tenant = tenant
        self.retry_after_ms = retry_after_ms


class TenantLedger:
    """Outstanding-grant and queued-demand counts per tenant.

    The dispatcher charges at grant mint and releases on every exit
    path (free, expire, zombie-kill, adoption hand-back), so
    ``outstanding`` is exact, not sampled.  Queued demand is the
    pending-waiter immediate count, charged while a request waits.
    """

    def __init__(self, directory: Optional[TenantDirectory] = None):
        self._directory = directory
        self._lock = threading.Lock()
        self._outstanding: Dict[str, int] = {}  # guarded by: self._lock
        self._queued: Dict[str, int] = {}  # guarded by: self._lock

    def _spec(self, tenant: str) -> Optional[TenantSpec]:
        if not tenant or self._directory is None:
            return None
        return self._directory.get(tenant)

    def charge(self, tenant: str, n: int = 1) -> None:
        if not tenant:
            return
        with self._lock:
            self._outstanding[tenant] = self._outstanding.get(tenant, 0) + n

    def release(self, tenant: str, n: int = 1) -> None:
        if not tenant:
            return
        with self._lock:
            left = self._outstanding.get(tenant, 0) - n
            if left > 0:
                self._outstanding[tenant] = left
            else:
                self._outstanding.pop(tenant, None)

    def charge_queued(self, tenant: str, n: int = 1) -> None:
        if not tenant:
            return
        with self._lock:
            self._queued[tenant] = self._queued.get(tenant, 0) + n

    def release_queued(self, tenant: str, n: int = 1) -> None:
        if not tenant:
            return
        with self._lock:
            left = self._queued.get(tenant, 0) - n
            if left > 0:
                self._queued[tenant] = left
            else:
                self._queued.pop(tenant, None)

    def outstanding(self, tenant: str) -> int:
        with self._lock:
            return self._outstanding.get(tenant, 0)

    def queued(self, tenant: str) -> int:
        with self._lock:
            return self._queued.get(tenant, 0)

    def over_budget(self, tenant: str, want_immediate: int = 0) -> bool:
        """Would granting ``want_immediate`` more put the tenant over
        either budget?  Tenants without a directory row (or with 0
        limits) are unbudgeted — budgets are an opt-in bound, identity
        is the fail-closed part."""
        spec = self._spec(tenant)
        if spec is None:
            return False
        with self._lock:
            out = self._outstanding.get(tenant, 0)
            queued = self._queued.get(tenant, 0)
        if spec.max_outstanding and out + want_immediate > spec.max_outstanding:
            return True
        if spec.max_queued and queued >= spec.max_queued:
            return True
        return False

    def inspect(self) -> dict:
        with self._lock:
            return {
                "outstanding": dict(self._outstanding),
                "queued": dict(self._queued),
            }


class CacheBytesLedger:
    """Write-quota accounting per cache namespace (keys.key_namespace).

    Tracks an UPPER BOUND on live bytes: per-key sizes are remembered
    so a same-key overwrite adjusts rather than double-counts, but
    evictions below this service are not observed — the quota bounds
    what a tenant may *write into* the cache, which is the poisoning/
    flooding vector budgets exist for.  The legacy "" namespace (shared
    single-tenant domain) is never budgeted.
    """

    def __init__(self, budgets: Optional[Dict[str, int]] = None):
        # namespace tag -> byte budget (0/absent = unlimited).
        self._budgets = dict(budgets or {})
        self._lock = threading.Lock()
        self._key_bytes: Dict[str, Dict[str, int]] = {}  # guarded by: self._lock
        self._usage: Dict[str, int] = {}  # guarded by: self._lock
        self._rejected: Dict[str, int] = {}  # guarded by: self._lock

    def set_budget(self, namespace: str, budget_bytes: int) -> None:
        with self._lock:
            if budget_bytes:
                self._budgets[namespace] = budget_bytes
            else:
                self._budgets.pop(namespace, None)

    def try_charge(self, namespace: str, key: str, size: int) -> bool:
        """Account one fill; False = over budget (caller must refuse
        the write).  Unbudgeted namespaces always charge successfully
        (usage is still tracked for inspect())."""
        if not namespace:
            return True
        with self._lock:
            per_key = self._key_bytes.setdefault(namespace, {})
            old = per_key.get(key, 0)
            budget = self._budgets.get(namespace, 0)
            new_usage = self._usage.get(namespace, 0) - old + size
            if budget and new_usage > budget:
                self._rejected[namespace] = self._rejected.get(namespace, 0) + 1
                return False
            per_key[key] = size
            self._usage[namespace] = new_usage
            return True

    def usage(self, namespace: str) -> int:
        with self._lock:
            return self._usage.get(namespace, 0)

    def inspect(self) -> dict:
        with self._lock:
            return {
                "usage_bytes": dict(self._usage),
                "budgets": dict(self._budgets),
                "rejected_fills": dict(self._rejected),
            }
