"""Tenant-domain cache-key derivation.

The determinism that makes fleet-wide cache sharing valuable is also
the leak: identical computations hash to identical keys (PAPERS.md,
Frostig et al.), so tenant B can *guess* tenant A's plaintext cache key
from public inputs and either read A's artifact or poison the entry A
will read next.  Prefix conventions don't help — B can write any key
string it likes.  Isolation must be cryptographic:

    tenant key = "ytpu-t-" + ns + "-" + MAC
    ns  = BLAKE2b(person="ytpu-tenant-ns",    key_secret)[:16]
    MAC = BLAKE2b(person="ytpu-tenant-cache", key_secret, plain_key)

``key_secret`` is the tenant's stable cache secret
(identity.tenant_key_secret), held only by trusted daemons.  Without
it, B can neither compute A's key for a known computation (no read)
nor produce a key A will later derive (no poison) — a forged write
lands in whatever namespace B's own secret spans.  The ``ns`` tag is
deliberately public-by-construction (it reveals *which* tenant, never
*what* computation): the cache service groups per-tenant usage
accounting and byte budgets by it without holding any secrets.

An EMPTY secret returns the plaintext key unchanged.  That is the
single-tenant/legacy mode: every historical entry, the dataplane
parity gate, and any deployment that never configures tenancy keep
byte-identical keys.

Shared probabilistic structures stay shared.  Bloom filters and
prefetch traces operate on these derived keys: a membership bit or a
trace line reveals only that *some* opaque MAC exists, and without the
tenant secret no observer can map a MAC back to a computation or
derive a colliding key — so sharing them across tenants leaks nothing
useful (doc/tenancy.md "Threat model").
"""

from __future__ import annotations

from yadcc_tpu.common.hashing import digest_keyed

_SCOPED_PREFIX = "ytpu-t-"
_NS_DOMAIN = "ytpu-tenant-ns"
_MAC_DOMAIN = "ytpu-tenant-cache"
_NS_HEX_LEN = 16


def tenant_scoped_key(tenant_secret: str, key: str) -> str:  # ytpu: sanitizes(tenant-domain, key-domain)
    """Derive the tenant-scoped form of ``key``.

    Empty ``tenant_secret`` is the legacy/shared domain: the key passes
    through unchanged (byte-for-byte compatible with every entry ever
    written).  The derived form keeps no plaintext: the MAC covers the
    full original key, prefix included, so the per-workload versioned
    namespaces (``ytpu-cxx2-entry-`` ...) survive inside the MAC domain.
    """
    if not tenant_secret:
        return key
    ns = digest_keyed(_NS_DOMAIN, tenant_secret.encode())[:_NS_HEX_LEN]
    mac = digest_keyed(_MAC_DOMAIN, tenant_secret.encode(), key.encode())
    return f"{_SCOPED_PREFIX}{ns}-{mac}"


def key_namespace(key: str) -> str:
    """The public namespace tag of a scoped key; "" for legacy keys.

    The cache service keys its per-tenant byte ledgers on this — it
    needs no secrets, only the ability to group writes by tenant.
    """
    if not key.startswith(_SCOPED_PREFIX):
        return ""
    rest = key[len(_SCOPED_PREFIX):]
    ns, sep, mac = rest.partition("-")
    if not sep or len(ns) != _NS_HEX_LEN or not mac:
        return ""
    return ns
