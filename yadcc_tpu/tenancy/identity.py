"""Tenant identity: credentials, tiers, and the tenant directory.

Credentials ride the scheduler's existing trust anchor instead of
inventing a second one.  The scheduler already rotates a serving-daemon
token window hourly (scheduler/service.py ServingDaemonTokenRoll) and
every daemon learns the acceptable window via GetConfig/Heartbeat.  A
tenant credential is an HMAC sub-token of a window token:

    ytpu-tn1.<tenant_id>.<mac>
    mac = BLAKE2b(person="ytpu-tenant-cred", window_token, tenant_id)[:32]

Properties this buys for free:

* **Offline-derivable** — any component holding a window token (the
  delegate daemon, the scheduler, a provisioning job) can mint a
  tenant's credential without a round trip or a credential database.
* **Revocable by rotation** — credentials die with their window token;
  the whole fleet's tenant credentials roll over on the scheduler's
  existing hourly cadence with zero extra machinery.
* **Fail-closed** — verification against an EMPTY acceptable-token set
  rejects everything, exactly like the daemon-token check it mirrors.

The *cache* secret is deliberately NOT derived from the rotating
window: cache keys must survive rotation or every tenant would go cold
hourly.  ``tenant_key_secret`` derives a stable per-tenant secret from
a long-lived root secret held only by trusted infrastructure (the
delegate daemon and the servant — never the client), so tenant B can
neither compute tenant A's cache namespace nor forge entries into it.
See keys.py for the key derivation itself and doc/tenancy.md for the
threat model.
"""

from __future__ import annotations

import hmac
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, Optional

from yadcc_tpu.common.hashing import digest_keyed

# Fairness classes (tiers), ordered most- to least-latency-sensitive.
# The tier decides when a tenant is shed by the overload ladder
# (tiers.TIER_SHED_RUNG) and how wide it may fan out (TIER_FANOUT_CAPS).
TIER_INTERACTIVE = "interactive"
TIER_BATCH = "batch"
TIER_BEST_EFFORT = "best_effort"
TIERS = (TIER_INTERACTIVE, TIER_BATCH, TIER_BEST_EFFORT)

_CRED_PREFIX = "ytpu-tn1"
_CRED_DOMAIN = "ytpu-tenant-cred"
_ROOT_DOMAIN = "ytpu-tenant-root"
_MAC_HEX_LEN = 32


def derive_tenant_credential(window_token: str, tenant_id: str) -> str:
    """Mint the credential for ``tenant_id`` under one window token.

    Dots delimit the wire form, so tenant ids must not contain them;
    ids are operator-assigned short names (org slugs), not user input.
    """
    if not window_token or not tenant_id or "." in tenant_id:
        raise ValueError("tenant_id must be non-empty and dot-free")
    mac = digest_keyed(_CRED_DOMAIN, window_token.encode(),
                       tenant_id.encode())[:_MAC_HEX_LEN]
    return f"{_CRED_PREFIX}.{tenant_id}.{mac}"


def verify_tenant_credential(credential: str,
                             acceptable_tokens: Iterable[str]
                             ) -> Optional[str]:
    """Verify a credential against the acceptable window tokens.

    Returns the tenant id on success, None otherwise.  Fail-closed: an
    empty window rejects everything.  Comparison is constant-time per
    candidate token (hmac.compare_digest), mirroring the hardened
    daemon-token check in daemon_service._verify.
    """
    if not credential:
        return None
    parts = credential.split(".")
    if len(parts) != 3 or parts[0] != _CRED_PREFIX:
        return None
    tenant_id, mac = parts[1], parts[2]
    if not tenant_id or "." in tenant_id:
        return None
    ok = False
    for token in acceptable_tokens:
        want = digest_keyed(_CRED_DOMAIN, token.encode(),
                            tenant_id.encode())[:_MAC_HEX_LEN]
        # No early exit: every candidate is compared so timing does not
        # reveal which window position (if any) matched.
        if hmac.compare_digest(mac, want):
            ok = True
    return tenant_id if ok else None


def tenant_key_secret(root_secret: str, tenant_id: str) -> str:
    """Stable per-tenant cache secret, derived from the long-lived root.

    Held by trusted infrastructure only (delegate + servant).  Knowing
    one tenant's secret reveals nothing about another's — each is an
    independent keyed digest of the root.
    """
    if not root_secret or not tenant_id:
        return ""
    return digest_keyed(_ROOT_DOMAIN, root_secret.encode(),
                        tenant_id.encode())


@dataclass(frozen=True)
class TenantSpec:
    """Operator-declared per-tenant policy (the directory row)."""

    tenant_id: str
    tier: str = TIER_BATCH
    # Fairness weight at the tenant stride level (FairGrantQueue): two
    # tenants with weights 3 and 1 share grants 3:1 under contention.
    weight: float = 1.0
    # Scheduler-side budget: outstanding grants this tenant may hold
    # across the pool.  0 = unlimited.
    max_outstanding: int = 0
    # Scheduler-side budget: immediate demand this tenant may have
    # queued (pending waiters) before new asks are refused.  0 = unlimited.
    max_queued: int = 0
    # Cache-fill write quota in bytes (cache/service.py).  0 = unlimited.
    cache_bytes_budget: int = 0
    # Fan-out width cap for this tenant's AOT/autotune expansions;
    # 0 = the tier default (tiers.TIER_FANOUT_CAPS).
    fanout_cap: int = 0


@dataclass(frozen=True)
class TenantBinding:
    """A verified identity plus everything the dataplane needs from it.

    Produced by TenancyControl.authenticate; stamped onto tasks at the
    delegate HTTP surface and threaded to the scheduler and the cache
    key derivation.  ``key_secret`` never leaves trusted daemons.
    """

    tenant_id: str
    tier: str
    weight: float
    key_secret: str
    spec: TenantSpec


class TenantDirectory:
    """The set of tenants this cell serves.

    Fail-closed: authenticating a credential for a tenant id that has
    no directory row is a rejection, not a default admission — an
    attacker who mints a syntactically valid credential for a made-up
    tenant (possible for anyone holding a window token) still gets 403.
    """

    def __init__(self, specs: Iterable[TenantSpec] = ()):
        self._specs: Dict[str, TenantSpec] = {}
        for spec in specs:
            self.add(spec)

    def add(self, spec: TenantSpec) -> None:
        if spec.tier not in TIERS:
            raise ValueError(f"unknown tier {spec.tier!r}")
        self._specs[spec.tenant_id] = spec

    def get(self, tenant_id: str) -> Optional[TenantSpec]:
        return self._specs.get(tenant_id)

    def tenant_ids(self) -> list:
        return sorted(self._specs)

    def __len__(self) -> int:
        return len(self._specs)


class TenancyControl:
    """Authentication + policy lookup for one trust surface.

    Wraps the three inputs every surface needs — the tenant directory,
    the long-lived root cache secret, and a provider of the currently
    acceptable window tokens — behind one ``authenticate`` call, so the
    delegate HTTP front end, the scheduler service, and tests all share
    the identical fail-closed path.
    """

    def __init__(self, directory: TenantDirectory, root_secret: str,
                 acceptable_tokens: Callable[[], Iterable[str]]):
        self.directory = directory
        self._root_secret = root_secret
        self._acceptable_tokens = acceptable_tokens
        self._lock = threading.Lock()
        self._stats = {"authenticated": 0, "rejected": 0}  # guarded by: self._lock

    def authenticate(self, credential: str) -> Optional[TenantBinding]:
        tenant_id = verify_tenant_credential(
            credential, self._acceptable_tokens())
        spec = self.directory.get(tenant_id) if tenant_id else None
        if spec is None:
            with self._lock:
                self._stats["rejected"] += 1
            return None
        with self._lock:
            self._stats["authenticated"] += 1
        return TenantBinding(
            tenant_id=spec.tenant_id, tier=spec.tier, weight=spec.weight,
            key_secret=tenant_key_secret(self._root_secret, spec.tenant_id),
            spec=spec)

    def credential_for(self, tenant_id: str) -> str:
        """Mint a credential under the newest acceptable token (test and
        provisioning convenience; offline derivation needs no server)."""
        tokens = list(self._acceptable_tokens())
        if not tokens:
            raise RuntimeError("no acceptable window tokens")
        return derive_tenant_credential(tokens[0], tenant_id)

    def inspect(self) -> dict:
        with self._lock:
            stats = dict(self._stats)
        return {"tenants": self.directory.tenant_ids(), "stats": stats}
