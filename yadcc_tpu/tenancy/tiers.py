"""Tier x admission-rung shedding matrix and per-tier fan-out rights.

The overload ladder (scheduler/admission.py) is tenant-blind: one
global rung decides for everyone.  Tiers make shedding *ordered*: as
the cell degrades, best-effort work is turned away first, batch second,
and interactive traffic keeps its grants until the ladder itself
refuses everyone.

    rung \\ tier       interactive   batch          best_effort
    NORMAL            grant         grant          grant
    SHED_OPTIONAL     grant (no pf) grant (no pf)  REJECT+retry
    SPILLOVER         grant         REJECT+retry   REJECT+retry
    LOCAL_ONLY        compile-local compile-local  compile-local
    REJECT            REJECT        REJECT         REJECT

``apply_tier`` only ever *escalates*: it converts an admission the
ladder would have granted into a native FLOW_REJECT with the ladder's
own retry-after once the rung reaches the tier's shed rung.  Ladder
verdicts at LOCAL_ONLY/REJECT pass through untouched — a tier is a
right to be shed later, never a bypass of the cell's survival valve.
Tier rejections are counted into the ladder's shed-pressure signal by
the caller exactly like native rejections, so a storm of best-effort
demand keeps the signal honest while being refused.

Fan-out rights follow the same ordering: an interactive tenant may hedge
and fan out wide (AOT topologies, autotune sweeps), best-effort gets a
narrow cap.  Enforced at the delegate via
``jit.fanout.checked_fanout_width(n, cap=tier_fanout_cap(tier))``.
"""

from __future__ import annotations

from yadcc_tpu.scheduler.admission import (
    FLOW_NONE,
    FLOW_REJECT,
    RUNG_LOCAL_ONLY,
    RUNG_REJECT,
    RUNG_SHED_OPTIONAL,
    RUNG_SPILLOVER,
    AdmissionDecision,
)
from yadcc_tpu.tenancy.identity import (
    TIER_BATCH,
    TIER_BEST_EFFORT,
    TIER_INTERACTIVE,
)

# The rung at which a tier's *admitted* requests start being refused.
# Interactive maps to RUNG_REJECT: only the ladder itself sheds it.
TIER_SHED_RUNG = {
    TIER_INTERACTIVE: RUNG_REJECT,
    TIER_BATCH: RUNG_SPILLOVER,
    TIER_BEST_EFFORT: RUNG_SHED_OPTIONAL,
}

# Fan-out width caps (children per expansion) by tier; the global
# DEFAULT_MAX_FANOUT_WIDTH (64) still applies on top.
TIER_FANOUT_CAPS = {
    TIER_INTERACTIVE: 64,
    TIER_BATCH: 16,
    TIER_BEST_EFFORT: 4,
}

# Retry-after handed out with a tier rejection when the ladder's own
# decision carried none (the ladder only computes one at RUNG_REJECT).
_TIER_RETRY_AFTER_MS = 500


def tier_shed_rung(tier: str) -> int:
    """Unknown/empty tiers shed first — fail-closed, like identity."""
    return TIER_SHED_RUNG.get(tier, RUNG_SHED_OPTIONAL)


def tier_fanout_cap(tier: str) -> int:
    return TIER_FANOUT_CAPS.get(tier, TIER_FANOUT_CAPS[TIER_BEST_EFFORT])


def apply_tier(decision: AdmissionDecision, tier: str) -> AdmissionDecision:
    """Escalate an admission decision per the tier matrix.

    No-tier callers ("" from a pre-tenancy daemon) are treated as
    best_effort by ``tier_shed_rung`` — an unauthenticated workload
    cannot outrank a paying batch tenant.
    """
    if decision.flow != FLOW_NONE or decision.rung >= RUNG_LOCAL_ONLY:
        return decision  # the ladder already shed; never soften it
    if decision.rung < tier_shed_rung(tier):
        return decision
    return AdmissionDecision(
        rung=decision.rung, flow=FLOW_REJECT,
        retry_after_ms=decision.retry_after_ms or _TIER_RETRY_AFTER_MS,
        prefetch_allowed=False, signal=decision.signal)
