"""Multi-tenant QoS: verified tenant identity, tiers, budgets, and
cryptographic cache isolation (doc/tenancy.md).

The yadcc lineage trusts one machine room: every daemon that knows the
rotating serving-daemon token is a peer, and every cache entry is
readable by anyone who can name its key.  The ROADMAP's "millions of
users" north star breaks both assumptions — many organizations share
one fleet, and the determinism that makes fleet-wide cache sharing
valuable (identical computations hash to identical keys) is exactly
what makes a cross-tenant cache read a leak.

This package threads a *verified* tenant identity from the client's
environment to the cache key:

``identity``   per-tenant credentials HMAC-derived from the scheduler's
               rotating token window (offline-derivable, revoked by
               window rotation), verified fail-closed at every surface.
``tiers``      the fairness classes — interactive / batch / best_effort
               — and the tier x admission-rung shedding matrix.
``budgets``    per-tenant outstanding-grant, queued-demand, and
               cache-bytes ledgers.
``keys``       the tenant-domain cache-key separator: one tenant can
               neither read nor poison another's entries even with a
               guessed plaintext key.
"""

from yadcc_tpu.tenancy.identity import (
    TIER_BATCH,
    TIER_BEST_EFFORT,
    TIER_INTERACTIVE,
    TenancyControl,
    TenantBinding,
    TenantDirectory,
    TenantSpec,
    derive_tenant_credential,
    tenant_key_secret,
    verify_tenant_credential,
)
from yadcc_tpu.tenancy.keys import key_namespace, tenant_scoped_key
from yadcc_tpu.tenancy.tiers import (
    TIER_FANOUT_CAPS,
    TIER_SHED_RUNG,
    apply_tier,
    tier_fanout_cap,
    tier_shed_rung,
)
from yadcc_tpu.tenancy.budgets import (
    CacheBytesLedger,
    TenantLedger,
    TenantOverBudget,
)

__all__ = [
    "TIER_BATCH",
    "TIER_BEST_EFFORT",
    "TIER_FANOUT_CAPS",
    "TIER_INTERACTIVE",
    "TIER_SHED_RUNG",
    "CacheBytesLedger",
    "TenancyControl",
    "TenantBinding",
    "TenantDirectory",
    "TenantLedger",
    "TenantOverBudget",
    "TenantSpec",
    "apply_tier",
    "derive_tenant_credential",
    "key_namespace",
    "tenant_key_secret",
    "tenant_scoped_key",
    "tier_fanout_cap",
    "tier_shed_rung",
    "verify_tenant_credential",
]
