"""Rule family 5: untrusted-taint — source → sanitizer → sink dataflow
across the trust boundary.

The servants execute bytes that arrive off the network; the delegate's
HTTP service buffers bytes from arbitrary local processes.  PRs 4-6
hand-placed the defenses (token fail-closed, claimed-digest
verification, decompression caps) at each intake — this pass makes the
discipline *structural*:

* **Sources** are declared on the intake functions with
  ``# ytpu: untrusted(req, attachment)`` trailing the ``def``.  A
  ``self.X`` entry marks an instance attribute as untrusted (the HTTP
  handler's ``self.rfile``/``self.headers``).
* **Sanitizers** are declared on the validation helpers with
  ``# ytpu: sanitizes(size-cap)`` (tags: ``size-cap``, ``path``,
  ``argv``, ``key-domain``, ``authz``, ``digest``, ``framing``...).
  Calling one applies its tags to the value (result and, for a bare
  ``self._verify(req.token)`` statement, to the argument's root).
  ``min(x, CONST)``/``max`` count as ``size-cap``; ``shlex.quote`` as
  ``argv``+``path``.
* **Sinks** require specific tags (core.SINK_REQUIRED_TAGS):
  allocation-sized reads (``size-cap``), timeout/wait durations
  (``size-cap``), filesystem path construction (``path``), subprocess
  argv (``argv``), cache keys (``key-domain``).

The pass is interprocedural by *summary*: each function records, on
the assumption its parameters are tainted, which sinks they reach and
which callees they flow into; a worklist then walks call edges from
the declared sources.  Callees resolve by name (method or function
last segment) — ambiguous names (>3 defs) and a stoplist of generic
verbs are skipped, erring toward false negatives like every other
family.  A tainted argument passed into a callee parameter whose name
says it is a duration (``timeout``/``*_to_wait``/...) is a wait sink at
the call site even when the callee body is opaque.

``taint-registry`` closes the workload seam: every ``TaskType(...)``
registration must name a factory that (transitively) routes its intake
through a ``sanitizes(size-cap)`` helper, so ROADMAP workloads 3-4
cannot land unvalidated by construction.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    SINK_REQUIRED_TAGS,
    Finding,
    FunctionInfo,
    ModuleModel,
    _dotted,
    last_segment,
    root_segment,
)

# Builtin sanitizers, by call last segment.
_BUILTIN_SANITIZERS: Dict[str, Set[str]] = {
    "quote": {"argv", "path"},          # shlex.quote
}
# Calls whose result carries no taint regardless of arguments.
_CLEAN_CALLS = {"len", "bool", "id", "hash", "isinstance", "hasattr",
                "type", "repr", "hex", "oct", "enumerate", "range"}
# Parser-shaped calls that must NOT be treated as constructors even
# though they are CamelCase: their output is as untrusted as the input.
_PARSE_THROUGH = {"FromString", "ParseFromString", "Parse", "loads",
                  "load", "fromhex"}
# Callee names too generic to resolve by name without drowning in
# cross-class aliasing.
_RESOLUTION_STOPLIST = {
    "get", "put", "add", "pop", "update", "append", "remove", "close",
    "start", "stop", "run", "call", "write", "join", "split", "items",
    "keys", "values", "copy", "encode", "decode", "send", "recv",
    "submit", "result", "acquire", "release", "format", "strip",
}
_MAX_CANDIDATES = 3
_MAX_HOPS = 8

_WAIT_PARAM_RE = re.compile(
    r"(timeout|deadline|to_wait|wait_s$|_secs$|seconds)", re.IGNORECASE)

_PATH_CALL_LAST = {"remove", "rename", "rmtree", "unlink", "mkdir",
                   "makedirs", "replace", "join", "open"}
_ARGV_CALL_LAST = {"Popen", "start_program", "system", "check_output",
                   "check_call", "run"}
_CACHE_KEY_LAST = {"async_write", "try_read"}

_RULE_FOR_SINK = {
    "alloc": "taint-alloc",
    "wait": "taint-wait",
    "path": "taint-path",
    "argv": "taint-argv",
    "cache-key": "taint-cache-key",
}


def _is_constructor_name(name: str) -> bool:
    return bool(name) and name[0].isupper() and not name.isupper() \
        and name not in _PARSE_THROUGH


class _Summarizer:
    """Single in-order walk of one function body, assuming every
    parameter is tainted; emits the JSON summary the global worklist
    consumes."""

    def __init__(self, info: FunctionInfo,
                 sanitizer_map: Dict[str, Set[str]]):
        self.info = info
        self.sanitizers = sanitizer_map
        self.params: Set[str] = set(info.params)
        # self.X pseudo-params from untrusted(self.X) declarations.
        self.pseudo: Set[str] = {u for u in info.untrusted
                                 if u.startswith("self.")}
        self.origins: Dict[str, Set[str]] = {}
        self.applied: Dict[str, Set[str]] = {}
        self.sinks: List[dict] = []
        self.calls: List[dict] = []
        self.all_callees: Set[str] = set()
        self.returns_origins: Set[str] = set()
        self._call_seen: Set[int] = set()

    # -- expression evaluation --------------------------------------------

    def _sanitizer_tags(self, name: Optional[str]) -> Optional[Set[str]]:
        if name is None:
            return None
        if name in self.sanitizers:
            return set(self.sanitizers[name])
        if name in _BUILTIN_SANITIZERS:
            return set(_BUILTIN_SANITIZERS[name])
        return None

    def _root_spec(self, node: ast.AST) -> Optional[str]:
        """Name -> its id; self.X... -> "self.X"; else None."""
        if isinstance(node, ast.Name):
            return node.id
        chain: List[str] = []
        n = node
        while isinstance(n, ast.Attribute):
            chain.append(n.attr)
            n = n.value
        if isinstance(n, ast.Name):
            if n.id == "self" and chain:
                return f"self.{chain[-1]}"
            return n.id
        return None

    def eval_expr(self, node: ast.AST) -> Tuple[Set[str], Set[str]]:
        """(origin params, applied sanitizer tags) of an expression."""
        if isinstance(node, ast.Name):
            name = node.id
            if name in self.origins:
                return set(self.origins[name]), \
                    set(self.applied.get(name, ()))
            if name in self.params and name != "self":
                return {name}, set(self.applied.get(name, ()))
            return set(), set()
        if isinstance(node, ast.Attribute):
            spec = self._root_spec(node)
            if spec in self.pseudo:
                return {spec}, set(self.applied.get(spec, ()))
            return self.eval_expr(node.value)
        if isinstance(node, ast.Call):
            self._visit_call(node)
            name = last_segment(node.func)
            if name in _CLEAN_CALLS:
                return set(), set()
            if name is not None and _is_constructor_name(name):
                # Constructed objects carry state, not data taint; the
                # attribute-level flow is out of scope (doc honesty).
                for a in node.args:
                    self.eval_expr(a)
                return set(), set()
            origins: Set[str] = set()
            tag_sets: List[Set[str]] = []
            values = list(node.args) + [kw.value for kw in node.keywords]
            if isinstance(node.func, ast.Attribute):
                # A method call's result derives from its receiver too
                # (`self.headers.get(...)` is as untrusted as headers).
                values.append(node.func.value)
            for a in values:
                o, t = self.eval_expr(a)
                if o:
                    origins |= o
                    tag_sets.append(t)
            applied = set.intersection(*tag_sets) if tag_sets else set()
            san = self._sanitizer_tags(name)
            if san is not None:
                applied |= san
            elif name in ("min", "max") and any(
                    isinstance(a, ast.Constant) for a in node.args):
                applied |= {"size-cap"}
            return origins, applied
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return set(), set()
        origins = set()
        tag_sets = []
        for child in ast.iter_child_nodes(node):
            o, t = self.eval_expr(child)
            if o:
                origins |= o
                tag_sets.append(t)
        return origins, (set.intersection(*tag_sets)
                         if tag_sets else set())

    # -- call inspection (sinks + interprocedural edges) -------------------

    def _arg_state(self, node: ast.AST) -> Tuple[Set[str], Set[str]]:
        return self.eval_expr(node)

    def _record_sink(self, kind: str, line: int, origins: Set[str],
                     applied: Set[str], detail: str) -> None:
        for origin in origins:
            self.sinks.append({"param": origin, "sink": kind,
                               "line": line,
                               "applied": sorted(applied),
                               "detail": detail})

    def _visit_call(self, node: ast.Call) -> None:
        if id(node) in self._call_seen:
            return
        self._call_seen.add(id(node))
        name = last_segment(node.func)
        if name is None:
            return
        self.all_callees.add(name)
        dotted = _dotted(node.func) or name
        root = root_segment(node.func)

        def arg0():
            return node.args[0] if node.args else None

        # Sinks -----------------------------------------------------------
        if name == "read" and node.args:
            o, t = self._arg_state(node.args[0])
            if o and "size-cap" not in t:
                self._record_sink("alloc", node.lineno, o, t,
                                  f"{dotted}(n)")
        if name == "bytearray" and node.args:
            o, t = self._arg_state(node.args[0])
            if o and "size-cap" not in t:
                self._record_sink("alloc", node.lineno, o, t,
                                  "bytearray(n)")
        if name == "sleep" and node.args:
            o, t = self._arg_state(node.args[0])
            if o and "size-cap" not in t:
                self._record_sink("wait", node.lineno, o, t,
                                  f"{dotted}(t)")
        for kw in node.keywords:
            if kw.arg and _WAIT_PARAM_RE.search(kw.arg):
                o, t = self._arg_state(kw.value)
                if o and "size-cap" not in t:
                    self._record_sink("wait", node.lineno, o, t,
                                      f"{dotted}({kw.arg}=...)")
        if name in _PATH_CALL_LAST and (root in ("os", "shutil", "Path")
                                        or name == "open"):
            a = arg0()
            if a is not None:
                o, t = self._arg_state(a)
                if o and "path" not in t:
                    self._record_sink("path", node.lineno, o, t,
                                      f"{dotted}(...)")
        if name == "Path" and node.args:
            o, t = self._arg_state(node.args[0])
            if o and "path" not in t:
                self._record_sink("path", node.lineno, o, t, "Path(...)")
        if name in _ARGV_CALL_LAST:
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                o, t = self._arg_state(a)
                if o and "argv" not in t:
                    self._record_sink("argv", node.lineno, o, t,
                                      f"{dotted}(...)")
        if name in _CACHE_KEY_LAST and node.args:
            o, t = self._arg_state(node.args[0])
            # Record unless the FULL required set is already applied:
            # a key-domain-only derivation must still reach the
            # worklist so the missing tenant-domain separator is
            # reported (doc/tenancy.md).
            if o and not SINK_REQUIRED_TAGS["cache-key"] <= t:
                self._record_sink("cache-key", node.lineno, o, t,
                                  f"{dotted}(key)")

        # Interprocedural edge --------------------------------------------
        if name in _RESOLUTION_STOPLIST or name in _CLEAN_CALLS \
                or self._sanitizer_tags(name) is not None:
            return
        args: List[dict] = []
        for i, a in enumerate(node.args):
            o, t = self._arg_state(a)
            if o:
                args.append({"pos": i, "kw": None,
                             "origins": sorted(o), "applied": sorted(t)})
        for kw in node.keywords:
            if kw.arg is None:
                continue
            o, t = self._arg_state(kw.value)
            if o:
                args.append({"pos": None, "kw": kw.arg,
                             "origins": sorted(o), "applied": sorted(t)})
        if args:
            self.calls.append({
                "callee": name, "line": node.lineno,
                "method": isinstance(node.func, ast.Attribute),
                "args": args,
            })

    # -- statement walk ----------------------------------------------------

    def _assign(self, target: ast.AST, origins: Set[str],
                applied: Set[str]) -> None:
        if isinstance(target, ast.Name):
            self.origins[target.id] = origins
            self.applied[target.id] = applied
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._assign(el, set(origins), set(applied))
        # Attribute / subscript stores: object state is out of scope.

    def walk(self, stmts: Sequence[ast.AST]) -> None:
        for stmt in stmts:
            self._walk_stmt(stmt)

    def _walk_stmt(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # summarized separately, without closure context
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = node.value
            if value is None:
                return
            o, t = self.eval_expr(value)
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if isinstance(node, ast.AugAssign) and \
                        isinstance(tgt, ast.Name):
                    prev = self.origins.get(tgt.id, set())
                    o = o | prev
                self._assign(tgt, o, t)
            return
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
            name = last_segment(call.func)
            san = self._sanitizer_tags(name)
            self.eval_expr(call)
            if san is not None:
                # Statement-form sanitizer (`self._verify(req.token)`)
                # blesses the argument roots from here on.
                for a in list(call.args) + [kw.value
                                            for kw in call.keywords]:
                    spec = self._root_spec(a)
                    if spec:
                        self.applied.setdefault(spec, set()).update(san)
            return
        if isinstance(node, ast.Return):
            if node.value is not None:
                o, _ = self.eval_expr(node.value)
                self.returns_origins |= o
            return
        if isinstance(node, (ast.If, ast.While)):
            self.eval_expr(node.test)
            self.walk(node.body)
            self.walk(node.orelse)
            return
        if isinstance(node, ast.For):
            o, t = self.eval_expr(node.iter)
            self._assign(node.target, o, t)
            self.walk(node.body)
            self.walk(node.orelse)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                o, t = self.eval_expr(item.context_expr)
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, o, t)
            self.walk(node.body)
            return
        if isinstance(node, ast.Try):
            self.walk(node.body)
            for h in node.handlers:
                self.walk(h.body)
            self.walk(node.orelse)
            self.walk(node.finalbody)
            return
        if isinstance(node, (ast.Raise, ast.Assert, ast.Delete)):
            for child in ast.iter_child_nodes(node):
                self.eval_expr(child)
            return
        if isinstance(node, ast.Expr):
            self.eval_expr(node.value)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._walk_stmt(child)
            else:
                self.eval_expr(child)


def summarize_function(info: FunctionInfo,
                       sanitizer_map: Dict[str, Set[str]]) -> dict:
    s = _Summarizer(info, sanitizer_map)
    if info.node is not None:
        s.walk(info.node.body)
    return {
        "params": list(info.params),
        "pseudo": sorted(s.pseudo),
        "sinks": s.sinks,
        "calls": s.calls,
        "all_callees": sorted(s.all_callees),
        "returns": sorted(s.returns_origins),
    }


def summarize_functions(model: ModuleModel,
                        functions: List[FunctionInfo],
                        sanitizer_map: Dict[str, Set[str]]) -> None:
    for info in functions:
        info.taint = summarize_function(info, sanitizer_map)


# ---------------------------------------------------------------------------
# Global worklist.
# ---------------------------------------------------------------------------


def check_global(functions: Sequence[FunctionInfo],
                 tasktype_sites: Sequence[dict],
                 sanitizer_map: Dict[str, Set[str]]) -> List[Finding]:
    findings: List[Finding] = []
    by_name: Dict[str, List[FunctionInfo]] = {}
    by_qual: Dict[str, FunctionInfo] = {}
    for info in functions:
        by_name.setdefault(info.name, []).append(info)
        by_qual[info.qualname] = info

    # Seeds: declared untrusted params (and self.X pseudo-params).
    work: List[Tuple[str, str, frozenset, int]] = []
    for info in functions:
        for spec in info.untrusted:
            if spec.startswith("self.") or spec in info.params:
                work.append((info.qualname, spec, frozenset(), 0))
            else:
                findings.append(Finding(
                    "taint-registry", info.relpath, info.lineno,
                    f"untrusted({spec}) names no parameter of "
                    f"{info.name}"))

    visited: Set[Tuple[str, str, frozenset]] = set()
    emitted: Set[Tuple[str, str, int, str]] = set()

    def emit(rule: str, relpath: str, line: int, msg: str) -> None:
        key = (rule, relpath, line, msg)
        if key not in emitted:
            emitted.add(key)
            findings.append(Finding(rule, relpath, line, msg))

    while work:
        qual, param, inherited, hops = work.pop()
        key = (qual, param, inherited)
        if key in visited or hops > _MAX_HOPS:
            continue
        visited.add(key)
        info = by_qual.get(qual)
        if info is None or not info.taint:
            continue
        summary = info.taint
        for sink in summary["sinks"]:
            if sink["param"] != param:
                continue
            effective = inherited | set(sink["applied"])
            required = SINK_REQUIRED_TAGS[sink["sink"]]
            missing = required - effective
            if missing:
                emit(_RULE_FOR_SINK[sink["sink"]], info.relpath,
                     sink["line"],
                     f"untrusted '{param}' in {info.name} reaches "
                     f"{sink['detail']} without a "
                     f"{'/'.join(sorted(missing))} sanitizer")
        for call in summary["calls"]:
            callee = call["callee"]
            cands = by_name.get(callee, [])
            if not cands or len(cands) > _MAX_CANDIDATES:
                continue
            for arg in call["args"]:
                if param not in arg["origins"]:
                    continue
                effective = inherited | set(arg["applied"])
                for cand in cands:
                    if not cand.taint:
                        continue
                    plist = list(cand.taint["params"])
                    if call["method"] and plist and plist[0] == "self":
                        plist = plist[1:]
                    target: Optional[str] = None
                    if arg["kw"] is not None:
                        if arg["kw"] in plist:
                            target = arg["kw"]
                    elif arg["pos"] is not None and \
                            arg["pos"] < len(plist):
                        target = plist[arg["pos"]]
                    if target is None:
                        continue
                    if _WAIT_PARAM_RE.search(target) and \
                            "size-cap" not in effective:
                        emit("taint-wait", info.relpath, call["line"],
                             f"untrusted '{param}' controls "
                             f"{callee}({target}=...) without a "
                             f"size-cap sanitizer")
                    work.append((cand.qualname, target,
                                 frozenset(effective), hops + 1))

    findings.extend(_check_registry(tasktype_sites, by_name,
                                    sanitizer_map))
    return findings


def _reaches_sanitizer(name: str, by_name: Dict[str, List[FunctionInfo]],
                       sanitizer_map: Dict[str, Set[str]],
                       want: str = "size-cap",
                       depth: int = 4,
                       class_methods: Optional[
                           Dict[str, List[FunctionInfo]]] = None
                       ) -> bool:
    """Does `name` (a factory) transitively call a helper annotated
    ``sanitizes(<want>...)``?

    ``class_methods`` (class name -> its method FunctionInfos) lets the
    walk hop through a constructor: a factory that builds
    ``CxxTask(...)`` reaches whatever the task's OWN methods reach.
    The hop resolves methods by identity, not by bare name — a
    same-named method on an unrelated class (every task class defines
    ``get_cache_key``) must not lend its sanitizers to this one."""

    def _scan(info: FunctionInfo, nxt: List[str]) -> bool:
        if want in info.sanitizes:
            return True
        if info.taint:
            for call in info.taint["calls"]:
                nxt.append(call["callee"])
            # calls without tainted args are not recorded in the
            # taint summary; fall back to the sink/call-free scan
            # recorded at summary time via all_callees.
            for c in info.taint.get("all_callees", ()):
                nxt.append(c)
        return False

    seen: Set[str] = set()
    frontier = [name]
    for _ in range(depth + 1):
        nxt: List[str] = []
        for n in frontier:
            if n in seen:
                continue
            seen.add(n)
            if want in sanitizer_map.get(n, set()):
                return True
            if class_methods and n in class_methods:
                for info in class_methods[n]:
                    if _scan(info, nxt):
                        return True
                continue
            for info in by_name.get(n, []):
                if _scan(info, nxt):
                    return True
        frontier = nxt
        if not frontier:
            break
    return False


def _check_registry(tasktype_sites: Sequence[dict],
                    by_name: Dict[str, List[FunctionInfo]],
                    sanitizer_map: Dict[str, Set[str]]
                    ) -> List[Finding]:
    findings: List[Finding] = []
    # Class name -> its method infos, for the constructor hop (a
    # factory's cache keys are derived by the task object it builds).
    class_methods: Dict[str, List[FunctionInfo]] = {}
    for infos in by_name.values():
        for info in infos:
            if "." in info.qualname:
                cls = info.qualname.rsplit(".", 2)[-2]
                class_methods.setdefault(cls, []).append(info)
    for site in tasktype_sites:
        kind = site.get("kind") or "?"
        factories = [f for f in site.get("factories", ())
                     if f in by_name or f in sanitizer_map]
        ok = any(_reaches_sanitizer(f, by_name, sanitizer_map)
                 for f in factories)
        if not ok:
            findings.append(Finding(
                "taint-registry", site["relpath"], site["line"],
                f"TaskType kind={kind!r}: make_task factory "
                f"{site.get('factories') or '<unresolved>'} cannot be "
                f"proven to route its intake through a "
                f"sanitizes(size-cap) validation helper"))
        # Tenancy seam (doc/tenancy.md): a kind that derives cache keys
        # (reaches a key-domain helper) must derive them through the
        # tenant-domain separator too, or its artifacts land in one
        # shared namespace and the cryptographic isolation silently
        # ends at this workload.  Kinds with no cache surface have
        # nothing to scope and are exempt.
        derives = any(_reaches_sanitizer(f, by_name, sanitizer_map,
                                         want="key-domain",
                                         class_methods=class_methods)
                      for f in factories)
        if derives and not any(
                _reaches_sanitizer(f, by_name, sanitizer_map,
                                   want="tenant-domain",
                                   class_methods=class_methods)
                for f in factories):
            findings.append(Finding(
                "taint-registry", site["relpath"], site["line"],
                f"TaskType kind={kind!r}: derives cache keys without "
                f"the sanitizes(tenant-domain) separator "
                f"(tenancy/keys.py tenant_scoped_key) — artifacts "
                f"would share one namespace across tenants"))
    return findings
