"""ytpu-analyze: AST-based concurrency & jit-discipline analyzer.

The reference keeps concurrency honest by convention (`Unsafe*` naming
for lock-held methods, a documented lock ordering,
task_dispatcher.h:226-268, and gperftools strict heap checking baked
into every test run, BLADE_ROOT:25-33).  Our port replicates the
conventions — `*_locked` method suffixes, leaf locks, a runtime
lock-order tracer (utils/locktrace.py) — but until this package nothing
checked them *statically*: a guarded field touched outside its lock or
a device sync under the dispatcher lock only surfaced if a stress test
happened to hit the interleaving.  This is the lint-time tier
(`python -m yadcc_tpu.analysis yadcc_tpu`, `make lint`): a TSan-style
static pass over the package's own source.

Rule families (doc/static_analysis.md has the full catalog):

* ``guarded-by`` / ``locked-call`` — attributes declared via
  ``# guarded by: self._lock`` trailing comments may only be touched
  while that lock is held (a ``with self._lock:`` block, a Condition
  constructed over it, or a ``*_locked`` method, which by convention
  runs with the class's primary lock held); ``self.*_locked()`` calls
  require the lock too.
* ``lock-order`` — nested ``with`` acquisitions are extracted as
  edges and checked against the declared hierarchy
  (analysis/lock_hierarchy.toml); complements the runtime locktrace,
  which sees cross-function/cross-class orderings this pass cannot.
* ``block-under-lock`` — sleeps, file/socket I/O, RPC calls, device
  sync / jnp dispatch inside a lock body in scheduler/ and daemon/
  hot paths (the sub-2ms grant budget leaves no room for any of them).
* ``jit-nondet`` / ``jit-tracer-if`` / ``jit-static-unhashable`` —
  jit hygiene inside ``@jax.jit`` functions in ops/ and parallel/.
* ``taint-*`` — interprocedural untrusted-taint: sources declared
  ``# ytpu: untrusted(...)`` on the network intake functions,
  sanitizers declared ``# ytpu: sanitizes(size-cap|key-domain|...)``
  on the validation helpers, sinks = allocations/waits/paths/argv/
  cache keys; ``taint-registry`` proves every registered TaskType
  routes its intake through validation (taint.py).
* ``lifecycle-*`` — acquire/release pairing across exception paths
  for temp workspaces, handles, pools and subprocesses, plus
  ``# ytpu: acquires(...)`` receiver tracking and mutable-buffer view
  escapes (lifecycle.py).
* ``wire-*`` — api/protos ↔ committed gen descriptors ↔ the pinned
  golden (analysis/wire_golden.json) ↔ field accesses in handler code
  (wirecompat.py); renumbering a field fails lint before it breaks
  the byte-identical wire/cache invariant.

Findings carry rule id + file:line and honor
``# ytpu: allow(<rule>)  # reason`` suppressions (a suppression
without a written reason is itself a finding).  ``--baseline``,
``--stats`` and a content-hash result cache keep the gate incremental
and fast (doc/static_analysis.md).
"""

from __future__ import annotations

from .core import AnalyzerConfig, Finding, analyze_paths

__all__ = ["AnalyzerConfig", "Finding", "analyze_paths"]
