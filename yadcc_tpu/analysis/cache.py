"""Content-hash result cache for the analyzer.

``make lint`` runs on every push and before every test cycle; the
analyzer's cost is dominated by ``ast.parse`` + the per-file rule
walks, and almost no file changes between runs.  Entries key on:

* the file's content hash (sha256 of its source),
* the run's *global key* — the directive fingerprint (which names
  carry sanitizes/acquires/untrusted annotations anywhere in the tree;
  cross-file taint/lifecycle results depend on it) hashed together
  with the config digest,
* the analyzer fingerprint — a hash of the ``analysis/*.py`` sources
  themselves, so editing a rule invalidates everything without a
  version constant anyone could forget to bump.

The store is one JSON file (default
``~/.cache/ytpu-analyze/cache.json``), bounded to ``max_entries`` by
dropping oldest-inserted first.  Corruption of any kind degrades to a
cold run, never to an error."""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Optional


def default_cache_path() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or \
        os.path.join(os.path.expanduser("~"), ".cache")
    return os.path.join(base, "ytpu-analyze", "cache.json")


def analyzer_fingerprint() -> str:
    """Hash of the analyzer's own sources: any rule edit is a new
    cache universe."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for fname in sorted(os.listdir(pkg)):
        if fname.endswith((".py", ".toml")):
            try:
                with open(os.path.join(pkg, fname), "rb") as fp:
                    h.update(fname.encode())
                    h.update(fp.read())
            except OSError:
                pass
    return h.hexdigest()


class ResultCache:
    def __init__(self, path: Optional[str] = None,
                 max_entries: int = 4096):
        self.path = path or default_cache_path()
        self.max_entries = max_entries
        self._fp = analyzer_fingerprint()
        self._entries: Dict[str, dict] = {}
        self._dirty = False
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path, "r", encoding="utf-8") as fp:
                doc = json.load(fp)
            if doc.get("analyzer") == self._fp and \
                    isinstance(doc.get("entries"), dict):
                self._entries = doc["entries"]
        except (OSError, ValueError):
            self._entries = {}

    def _key(self, content_hash: str, global_key: str) -> str:
        return f"{content_hash}:{global_key}"

    def get(self, content_hash: str, global_key: str) -> Optional[dict]:
        entry = self._entries.get(self._key(content_hash, global_key))
        return entry if isinstance(entry, dict) else None

    def put(self, content_hash: str, global_key: str,
            record: dict) -> None:
        key = self._key(content_hash, global_key)
        self._entries.pop(key, None)
        self._entries[key] = record
        while len(self._entries) > self.max_entries:
            self._entries.pop(next(iter(self._entries)))
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fp:
                json.dump({"analyzer": self._fp,
                           "entries": self._entries}, fp)
            os.replace(tmp, self.path)
            self._dirty = False
        except OSError:
            pass
