"""Rule family 6: resource lifecycle — acquire/release pairing across
exception paths.

Leases, temp workspaces (daemon/temp_dir.py, cloud/temporary.py),
executor pools, file/socket handles and subprocesses must not leak
when the code between acquisition and release raises.  Three rules:

* ``lifecycle-leak`` — an acquired resource bound to a local that is
  never released, never ``with``-managed and never escapes (returned,
  stored on an object, handed to a constructor/container).
* ``lifecycle-exc-path`` — a release exists, but only in straight-line
  flow with raise-capable calls between acquire and release: the happy
  path cleans up, the exception path leaks.  A release inside a
  ``finally`` or an ``except`` handler (the re-raise cleanup idiom)
  counts as exception-safe.
* ``lifecycle-view-escape`` — a ``memoryview`` over a *local mutable*
  buffer (``bytearray``) escapes the function; the receiver holds a
  view whose contents the function's caller can no longer reason
  about.  (Views over immutable ``bytes``/request frames are the data
  plane's whole point and are fine — the backing buffer is pinned and
  frozen.)

Acquire sites are a builtin table (open/mkdtemp/TemporaryDir/socket/
Popen/ThreadPoolExecutor/...) plus any function annotated
``# ytpu: acquires(<tag>)`` — calling an annotated method marks its
*receiver* as holding the resource (``task.prepare(...)`` makes
``task`` the thing that must not leak), which is how the servant
handlers' workspace discipline is checked across files.

Ownership transfer is honest, not paranoid: returning the resource,
storing it on ``self``/a container, passing it to a CamelCase
constructor, or capturing it in a closure (builtin acquires only) all
hand responsibility to someone this pass cannot see — no finding.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from .core import (
    AnalyzerConfig,
    Finding,
    ModuleModel,
    last_segment,
    root_segment,
)

# Call last segment -> resource kind.
ACQUIRE_SEGS: Dict[str, str] = {
    "open": "file handle",
    "mkdtemp": "temp dir",
    "mkstemp": "temp file",
    "make_temp_dir": "temp dir",
    "TemporaryDir": "temp workspace",
    "NamedTemporaryFile": "temp file",
    "TemporaryFile": "temp file",
    "socket": "socket",
    "create_connection": "socket",
    "ThreadPoolExecutor": "executor pool",
    "ProcessPoolExecutor": "executor pool",
    "Popen": "subprocess",
    "start_program": "subprocess",
}

RELEASE_SEGS = {"close", "remove", "shutdown", "terminate", "kill",
                "release", "stop", "cleanup", "wait", "rmtree",
                "unlink", "communicate", "__exit__"}

# Passing the resource into one of these transfers ownership to a
# container/pool the pass cannot track.
_TRANSFER_SEGS = {"append", "add", "put", "register", "submit",
                  "setdefault", "extend", "insert"}


class _Resource:
    def __init__(self, name: str, kind: str, line: int, order: int,
                 annotated: bool):
        self.names: Set[str] = {name}
        self.kind = kind
        self.line = line
        self.order = order
        self.annotated = annotated
        self.releases: List[dict] = []   # {"ctx": str, "order": int}
        self.escaped = False
        self.managed = False             # later used as `with res:`


class _FnChecker:
    def __init__(self, model: ModuleModel, fn: ast.AST,
                 acquires_names: Set[str], findings: List[Finding]):
        self.model = model
        self.fn = fn
        self.acquires_names = acquires_names
        self.findings = findings
        self.resources: List[_Resource] = []
        self.order = 0
        self.risky: List[int] = []       # order indexes of raise-capable calls
        self.mutable_locals: Set[str] = set()   # bytearray locals
        self.view_vars: Set[str] = set()        # views over them

    # -- helpers -----------------------------------------------------------

    def _res_for(self, name: str) -> Optional[_Resource]:
        for r in self.resources:
            if name in r.names:
                return r
        return None

    def _acquire_in(self, value: ast.AST) -> Optional[str]:
        """Kind when `value` is (or wraps) an acquire call."""
        for node in ast.walk(value):
            if isinstance(node, ast.Call):
                seg = last_segment(node.func)
                if seg in ACQUIRE_SEGS:
                    return ACQUIRE_SEGS[seg]
        return None

    # Calls that materialize a fresh value: a name passed INTO one of
    # these neither escapes nor transfers (``return bytes(view)`` is
    # the recommended fix for a view escape, not another escape).
    _MATERIALIZE = {"bytes", "str", "list", "tuple", "len", "sum",
                    "sorted", "min", "max", "int", "float", "bool",
                    "hash", "repr"}

    def _names_in(self, node: ast.AST) -> Set[str]:
        out: Set[str] = set()

        def visit(n: ast.AST) -> None:
            if isinstance(n, ast.Call) and \
                    last_segment(n.func) in self._MATERIALIZE:
                return
            if isinstance(n, ast.Name):
                out.add(n.id)
            for child in ast.iter_child_nodes(n):
                visit(child)

        visit(node)
        return out

    def _mark_escape(self, node: ast.AST) -> None:
        for name in self._names_in(node):
            r = self._res_for(name)
            if r is not None:
                r.escaped = True
            if name in self.view_vars:
                self.findings.append(Finding(
                    "lifecycle-view-escape", self.model.relpath,
                    getattr(node, "lineno", 1),
                    f"memoryview over local mutable buffer "
                    f"'{name}' escapes the function (hand out bytes, "
                    f"or let the caller own the buffer)"))
                self.view_vars.discard(name)

    # -- the walk ----------------------------------------------------------

    def run(self) -> None:
        self._walk(self.fn.body, ctx="plain")
        for r in self.resources:
            if r.managed or r.escaped:
                continue
            if not r.releases:
                self.findings.append(Finding(
                    "lifecycle-leak", self.model.relpath, r.line,
                    f"{r.kind} acquired here is never released, "
                    f"with-managed, or handed off"))
                continue
            if any(rel["ctx"] in ("finally", "except")
                   for rel in r.releases):
                continue
            first_rel = min(rel["order"] for rel in r.releases)
            if any(r.order < i < first_rel for i in self.risky):
                self.findings.append(Finding(
                    "lifecycle-exc-path", self.model.relpath, r.line,
                    f"{r.kind} released only on the happy path: calls "
                    f"between acquire and release can raise past the "
                    f"cleanup (use with / try-finally / except+raise)"))

    def _walk(self, stmts: Sequence[ast.AST], ctx: str) -> None:
        for stmt in stmts:
            self._stmt(stmt, ctx)

    def _stmt(self, node: ast.AST, ctx: str) -> None:
        self.order += 1
        order = self.order
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # Closure capture: a builtin-acquired resource referenced in
            # a nested def outlives this frame in ways we cannot track.
            for name in self._names_in(node):
                r = self._res_for(name)
                if r is not None and not r.annotated:
                    r.escaped = True
                if name in self.view_vars:
                    self.view_vars.discard(name)
            return
        if isinstance(node, ast.ClassDef):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                root = root_segment(item.context_expr)
                if root is not None:
                    r = self._res_for(root)
                    if r is not None:
                        r.managed = True
                self._scan_calls(item.context_expr, ctx)
            self._walk(node.body, ctx)
            return
        if isinstance(node, ast.Try):
            has_final = bool(node.finalbody)
            self._walk(node.body,
                       "try-with-finally" if has_final else ctx)
            for h in node.handlers:
                self._walk(h.body, "except")
            self._walk(node.orelse, ctx)
            self._walk(node.finalbody, "finally")
            return
        if isinstance(node, (ast.If, ast.While)):
            self._scan_calls(node.test, ctx)
            self._walk(node.body, ctx)
            self._walk(node.orelse, ctx)
            return
        if isinstance(node, ast.For):
            self._scan_calls(node.iter, ctx)
            self._walk(node.body, ctx)
            self._walk(node.orelse, ctx)
            return
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            if getattr(node, "value", None) is not None:
                self._mark_escape(node.value)
                self._scan_calls(node.value, ctx)
            return
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Yield):
            if node.value.value is not None:
                self._mark_escape(node.value.value)
            return
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = node.value
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if value is not None:
                self._scan_calls(value, ctx)
                name_target = targets[0] if len(targets) == 1 and \
                    isinstance(targets[0], ast.Name) else None
                # Acquisition into a local.  The resource's order is
                # taken AFTER scanning the value, so the acquire call
                # itself never reads as a risky call "between" acquire
                # and release.
                kind = self._acquire_in(value)
                annotated_recv = self._annotated_acquire_recv(value)
                if name_target is not None and kind is not None:
                    self.resources.append(_Resource(
                        name_target.id, kind, node.lineno, self.order,
                        False))
                elif annotated_recv is not None and name_target is not None:
                    self.resources.append(_Resource(
                        name_target.id, "annotated resource",
                        node.lineno, self.order, True))
                # Aliasing: y = x.
                if name_target is not None and isinstance(value, ast.Name):
                    r = self._res_for(value.id)
                    if r is not None:
                        r.names.add(name_target.id)
                    if value.id in self.view_vars:
                        self.view_vars.add(name_target.id)
                # bytearray locals + views over them.
                if name_target is not None and isinstance(value, ast.Call):
                    seg = last_segment(value.func)
                    if seg == "bytearray":
                        self.mutable_locals.add(name_target.id)
                    if seg == "memoryview" and value.args and \
                            isinstance(value.args[0], ast.Name) and \
                            value.args[0].id in self.mutable_locals:
                        self.view_vars.add(name_target.id)
                # Store to attribute/subscript = ownership transfer.
                for tgt in targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)) \
                            and value is not None:
                        self._mark_escape(value)
            return
        self._scan_calls(node, ctx)

    def _annotated_acquire_recv(self, value: ast.AST) -> Optional[str]:
        for node in ast.walk(value):
            if isinstance(node, ast.Call):
                seg = last_segment(node.func)
                if seg in self.acquires_names:
                    return seg
        return None

    def _scan_calls(self, node: ast.AST, ctx: str) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                for name in self._names_in(sub):
                    r = self._res_for(name)
                    if r is not None and not r.annotated:
                        r.escaped = True
                continue
            if not isinstance(sub, ast.Call):
                continue
            seg = last_segment(sub.func)
            self.order += 1
            order = self.order
            # Annotated acquire on a receiver: `task.prepare(...)`.
            if seg in self.acquires_names and \
                    isinstance(sub.func, ast.Attribute):
                root = root_segment(sub.func)
                if root is not None and root != "self" and \
                        self._res_for(root) is None:
                    self.resources.append(_Resource(
                        root, "annotated resource", sub.lineno, order,
                        True))
                    continue
            # Release?
            released = False
            if seg in RELEASE_SEGS:
                if isinstance(sub.func, ast.Attribute):
                    root = root_segment(sub.func)
                    r = self._res_for(root) if root else None
                    if r is not None:
                        r.releases.append({"ctx": ctx, "order": order})
                        released = True
                for a in sub.args:
                    if isinstance(a, ast.Name):
                        r = self._res_for(a.id)
                        if r is not None:
                            r.releases.append({"ctx": ctx,
                                               "order": order})
                            released = True
            if released:
                continue
            # Transfer?
            if seg is not None and (
                    (seg[0].isupper() and not seg.isupper())
                    or seg in _TRANSFER_SEGS):
                for a in list(sub.args) + [kw.value
                                           for kw in sub.keywords]:
                    self._mark_escape(a)
                continue
            # Any other call can raise.
            self.risky.append(order)


def check_module(model: ModuleModel, config: AnalyzerConfig,
                 acquires_names: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(model.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _FnChecker(model, node, acquires_names, findings).run()
    return findings
