"""Analyzer infrastructure: directives, lock discovery, held-lock walk.

Everything here is shared by the rule families (lockrules.py,
jit_hygiene.py): parsing ``# guarded by:`` / ``# ytpu: allow(...)``
comments, discovering which attributes of a class are locks (and which
Conditions wrap which locks), and walking a function body while
tracking the set of locks statically known to be held.

Scope and honesty notes (also in doc/static_analysis.md):

* The walk is intraprocedural.  ``*_locked`` methods are assumed to
  run with their class's *primary* lock held (``self._lock`` when the
  class has one, else its only lock attribute) — that is exactly the
  convention the suffix declares.  Cross-class and cross-function
  acquisition chains are the runtime locktrace's job.
* A nested ``def`` inherits the held set of its definition site.  For
  the synchronous helper-closure idiom this is right; a closure stashed
  and called later from another thread is invisible to this pass.
* Lock acquisition is recognized on ``with`` statements only.  Raw
  ``.acquire()``/``.release()`` pairs (the locktrace proxy internals)
  are not tracked.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULES: Dict[str, str] = {
    "guarded-by": "guarded attribute accessed without its declared lock",
    "locked-call": "*_locked method called from a site not holding the lock",
    "lock-order": "nested lock acquisition undeclared or inverting the "
                  "declared hierarchy (analysis/lock_hierarchy.toml)",
    "block-under-lock": "blocking call (sleep / I/O / RPC / device sync) "
                        "inside a lock body on a scheduler/daemon hot path",
    "aio-blocking": "blocking call (sleep / file or socket I/O / sync RPC "
                    ".call / bare wait) inside an async coroutine in the "
                    "event-loop front end (rpc/)",
    "jit-nondet": "wall-clock or nondeterminism call inside a @jax.jit "
                  "function",
    "jit-tracer-if": "Python branch on a traced argument inside a "
                     "@jax.jit function",
    "jit-static-unhashable": "unhashable value bound to a static jit "
                             "argument",
    "device-sync": "host-blocking device sync (np.asarray / device_get "
                   "/ block_until_ready) in a dispatcher-cycle module",
    "taint-alloc": "allocation / read sized by an untrusted integer "
                   "without a size-cap sanitizer",
    "taint-wait": "untrusted value controls a timeout/wait duration "
                  "without a size-cap sanitizer",
    "taint-path": "untrusted value reaches filesystem path construction "
                  "without a path sanitizer",
    "taint-argv": "untrusted value reaches subprocess argv without an "
                  "argv sanitizer (shlex.quote)",
    "taint-cache-key": "untrusted value used as a cache key without a "
                       "key-domain sanitizer",
    "taint-registry": "a registered TaskType whose factory cannot be "
                      "proven to route its intake through validation",
    "lifecycle-leak": "acquired resource neither released, escaped, nor "
                      "with-managed on some path",
    "lifecycle-exc-path": "resource released only on the happy path "
                          "(no with / try-finally / except cleanup)",
    "lifecycle-view-escape": "memoryview over a local mutable buffer "
                             "escapes the function",
    "wire-drift": "api/protos/*.proto disagrees with the committed "
                  "api/gen/*_pb2.py descriptor",
    "wire-golden": "wire format diverged from the committed golden "
                   "descriptor (analysis/wire_golden.json)",
    "wire-unknown-field": "message constructed with a field name the "
                          "descriptor does not define",
    "reply-drop": "a path through a responder-annotated handler or "
                  "continuation neither replies, hands the responder "
                  "off, nor raises (the parked client is dropped)",
    "reply-double": "a reachable second direct reply on one execution "
                    "path (double-fire into a settled stream)",
    "reply-handoff": "responder handed to a resolvable callee whose "
                     "receiving parameter is not declared "
                     "# ytpu: responder(param)",
    "await-under-lock": "await while a threading lock is held "
                        "(lexically or via the *_locked convention): "
                        "the whole event loop stalls behind the lock",
    "loop-affinity": "loop-only method called, or loop-affine "
                     "primitive (loop.call_later / create_task / "
                     "Future.set_result) used, outside loop context "
                     "without the call_soon_threadsafe seam",
    "async-timer-leak": "loop timer handle dropped at creation or "
                        "never cancelled / handed off: the timer "
                        "outlives the continuation it guards",
    "async-task-orphan": "asyncio task neither awaited, cancelled, "
                         "retained nor handed off (orphaned tasks "
                         "silently eat exceptions)",
    "repl-journal-skip": "a mutation path of a # ytpu: replicated(...) "
                         "method commits to the wrapped dispatcher "
                         "without a post-commit journal append (or "
                         "appends before the commit / on an exception "
                         "path)",
    "repl-journal-under-lock": "lease-journal append while a lock is "
                               "held: the rank-4 leaf journal must only "
                               "be taken at the call boundary, never "
                               "nested under dispatcher state locks",
    "grant-id-arith": "bare arithmetic on a grant id outside the "
                      "blessed namespace helpers, or a (start, stride) "
                      "construction that breaks the cell x shard "
                      "stride composition",
    "takeover-order": "a # ytpu: protocol(a<b<...) step reached on a "
                      "path where an earlier declared step has not "
                      "happened (e.g. promote before the adoption "
                      "window is established)",
    "suppression": "malformed suppression or suppression without a "
                   "written reason",
    "parse-error": "file could not be parsed",
}

# Sink kind -> sanitizer tags that clear it (taint family).  Cache
# keys require BOTH the versioned-prefix discipline (key-domain) and
# the tenant-domain separator (tenancy/keys.py): a key that reaches
# the store without passing through tenant_scoped_key (or a helper
# annotated as applying it) would silently merge tenants back into one
# namespace — exactly the cross-tenant read/poison surface the
# multi-tenant QoS tentpole closes (doc/tenancy.md).
SINK_REQUIRED_TAGS: Dict[str, frozenset] = {
    "alloc": frozenset({"size-cap"}),
    "wait": frozenset({"size-cap"}),
    "path": frozenset({"path"}),
    "argv": frozenset({"argv"}),
    "cache-key": frozenset({"key-domain", "tenant-domain"}),
}

# Factories whose call result is a lock / a condition.  Matched on the
# last dotted segment so `threading.Lock`, bare `Lock` (from-import) and
# locktrace's `_real_lock` all register.
LOCK_FACTORIES = {"Lock", "allocate_lock", "_real_lock"}
RLOCK_FACTORIES = {"RLock", "_real_rlock"}
COND_FACTORIES = {"Condition"}

# Methods in which unguarded access to guarded attributes is legal: the
# object is not yet (or no longer) shared.
CONSTRUCTION_METHODS = {"__init__", "__new__", "__post_init__", "__del__"}

_SUPPRESS_RE = re.compile(
    r"#\s*ytpu:\s*allow\(\s*([A-Za-z0-9_*,\- ]*)\s*\)\s*(.*)$")
_GUARD_RE = re.compile(r"#\s*guarded by:\s*([A-Za-z0-9_.\[\]'\"]+)\s*$")
# Trust-boundary directives (taint + lifecycle families).  All three
# ride the `def` line (or a line of its signature / the line directly
# above its first decorator) as trailing comments:
#
#   def decompress(data, cap):   # ytpu: sanitizes(size-cap)
#   def prepare(self, src):      # ytpu: acquires(workspace)
#   def QueueTask(self, req, attachment, ctx):  # ytpu: untrusted(req, attachment)
_SANITIZES_RE = re.compile(r"#\s*ytpu:\s*sanitizes\(\s*([A-Za-z0-9_,\- ]*)\s*\)")
_ACQUIRES_RE = re.compile(r"#\s*ytpu:\s*acquires\(\s*([A-Za-z0-9_,\- ]*)\s*\)")
_UNTRUSTED_RE = re.compile(
    r"#\s*ytpu:\s*untrusted\(\s*([A-Za-z0-9_.,\s]*)\s*\)")
# Async-protocol directives (asyncproto family).  Both ride the def
# line the same way the trust-boundary directives do:
#
#   def WaitParked(self, req, att, ctx, done):  # ytpu: responder(done)
#   def send_payload(self, seq, payload):       # ytpu: loop-only
_RESPONDER_RE = re.compile(
    r"#\s*ytpu:\s*responder\(\s*([A-Za-z0-9_,\s]*)\s*\)")
_LOOP_ONLY_RE = re.compile(r"#\s*ytpu:\s*loop-only\b")
# Replication-protocol directives (replproto family).  Both ride the
# def line like the trust-boundary directives:
#
#   def free_task(self, loc, gids):  # ytpu: replicated(free)
#     -> every mutation path of this method must pair the commit with a
#        post-commit journal append carrying one of the declared ops.
#   def takeover(self):  # ytpu: protocol(freeze<replay<adopt<window<promote)
#     -> declared step order; every path must hit steps in order.
_REPLICATED_RE = re.compile(
    r"#\s*ytpu:\s*replicated\(\s*([A-Za-z0-9_,\s]*)\s*\)")
_PROTOCOL_RE = re.compile(
    r"#\s*ytpu:\s*protocol\(\s*([A-Za-z0-9_<\s]*)\s*\)")


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "suppressed": self.suppressed}


def baseline_key(f: Finding) -> str:
    """Line-number-free identity for --baseline files: unrelated edits
    shifting a file must not invalidate the whole baseline."""
    import hashlib

    digest = hashlib.sha256(f.message.encode()).hexdigest()[:12]
    return f"{f.rule}|{f.path}|{digest}"


@dataclass
class Suppression:
    line: int
    rules: Set[str]
    reason: str
    used: bool = False


@dataclass
class AnalyzerConfig:
    # Path fragments selecting the modules where block-under-lock
    # applies (grant/compile hot paths; the cache server's disk engine
    # legitimately does I/O under its own lock and stays out).
    hot_path_fragments: Tuple[str, ...] = ("scheduler", "daemon")
    # Path fragments selecting the modules where jit hygiene applies.
    # device_pool.py rides along: it is the scheduler-side owner of the
    # jitted resident step and its static-arg discipline; placement.py
    # likewise owns the scored-spill launch's compiled-variant cache.
    jit_path_fragments: Tuple[str, ...] = ("ops", "parallel",
                                           "device_pool.py",
                                           "placement.py")
    # Path fragments selecting the modules where aio-blocking applies
    # (the event-loop front end: coroutines there must never block).
    # "cloud" pulls in daemon/cloud/ — the parked servant wait
    # (WaitForCompilationOutputParked + ExecutionEngine's async
    # completion surface) runs on the accept loop.
    aio_path_fragments: Tuple[str, ...] = ("rpc", "cloud")
    # Path fragments (filename parts) selecting the dispatcher-cycle
    # modules where device-sync applies: the device-resident dispatch
    # hot loop, where any unsanctioned np.asarray/block_until_ready
    # stalls the fused launch pipeline.  federation.py / replication.py
    # ride along (ISSUE 18): cell routing and journal replay sit on the
    # same cycle and must not host-sync either; placement.py (ISSUE 19)
    # hosts the scored-spill launch and its pick readback.
    device_sync_path_fragments: Tuple[str, ...] = (
        "device_pool.py", "shard_router.py", "policy.py",
        "task_dispatcher.py", "federation.py", "replication.py",
        "placement.py")
    # Path fragments (filename parts) selecting the modules where the
    # replication / exactly-once family (repl-journal-skip,
    # repl-journal-under-lock, grant-id-arith, takeover-order) applies.
    # Any file carrying a replicated(...)/protocol(...) directive is
    # in scope regardless of name.
    replproto_path_fragments: Tuple[str, ...] = (
        "replication.py", "federation.py", "shard_router.py",
        "task_dispatcher.py")
    # Path fragments selecting the modules where the async-protocol
    # family (reply-once / await-under-lock / loop-affinity /
    # async-lifecycle) applies: the three serving layers that host
    # parked continuations.
    asyncproto_path_fragments: Tuple[str, ...] = (
        "rpc", "scheduler", "daemon")
    # Lock hierarchy: canonical lock name -> rank (lower acquired
    # first).  Loaded from lock_hierarchy.toml by the CLI.
    lock_ranks: Dict[str, int] = field(default_factory=dict)
    # Report suppressions that matched nothing (kept off the CI default:
    # rule evolution must not turn a stale-but-documented allow into a
    # gate failure).
    strict_suppressions: bool = False
    # Committed golden wire descriptor (analysis/wire_golden.json).
    # None = skip the golden comparison (proto<->gen drift and unknown-
    # field checks still run whenever an api/protos tree is analyzed).
    wire_golden: Optional[str] = None

    def digest_fields(self) -> dict:
        """The fields a cached result depends on."""
        return {"hot": list(self.hot_path_fragments),
                "jit": list(self.jit_path_fragments),
                "aio": list(self.aio_path_fragments),
                "dsync": list(self.device_sync_path_fragments),
                "asyncproto": list(self.asyncproto_path_fragments),
                "replproto": list(self.replproto_path_fragments),
                "ranks": dict(self.lock_ranks)}


# ---------------------------------------------------------------------------
# Directives (comment-level annotations).
# ---------------------------------------------------------------------------


class Directives:
    """Per-file suppressions and guard declarations, by line number.

    Guard comments are associated with an attribute by
    build_module_model, which matches them against the line span of the
    ``self.X = ...`` statement they sit on (so the comment may ride the
    closing line of a multi-line assignment)."""

    def __init__(self, source: str):
        self.suppressions: Dict[int, Suppression] = {}
        self.guards: Dict[int, str] = {}   # lineno -> lock expr
        self.sanitizes: Dict[int, Set[str]] = {}   # lineno -> tags
        self.acquires: Dict[int, Set[str]] = {}    # lineno -> tags
        self.untrusted: Dict[int, List[str]] = {}  # lineno -> param specs
        self.responders: Dict[int, List[str]] = {}  # lineno -> param names
        self.loop_only: Set[int] = set()           # lineno set
        self.replicated: Dict[int, List[str]] = {}  # lineno -> journal ops
        self.protocol: Dict[int, List[str]] = {}   # lineno -> ordered steps
        for lineno, text in enumerate(source.splitlines(), start=1):
            if "#" not in text:
                continue
            m = _SUPPRESS_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                reason = m.group(2).strip().lstrip("#").strip()
                self.suppressions[lineno] = Suppression(
                    lineno, rules, reason)
            g = _GUARD_RE.search(text)
            if g:
                self.guards[lineno] = g.group(1)
            s = _SANITIZES_RE.search(text)
            if s:
                self.sanitizes[lineno] = {t.strip()
                                          for t in s.group(1).split(",")
                                          if t.strip()}
            a = _ACQUIRES_RE.search(text)
            if a:
                self.acquires[lineno] = {t.strip()
                                         for t in a.group(1).split(",")
                                         if t.strip()}
            u = _UNTRUSTED_RE.search(text)
            if u:
                self.untrusted[lineno] = [t.strip()
                                          for t in u.group(1).split(",")
                                          if t.strip()]
            r = _RESPONDER_RE.search(text)
            if r:
                self.responders[lineno] = [t.strip()
                                           for t in r.group(1).split(",")
                                           if t.strip()]
            if _LOOP_ONLY_RE.search(text):
                self.loop_only.add(lineno)
            rp = _REPLICATED_RE.search(text)
            if rp:
                self.replicated[lineno] = [t.strip()
                                           for t in rp.group(1).split(",")
                                           if t.strip()]
            pr = _PROTOCOL_RE.search(text)
            if pr:
                self.protocol[lineno] = [t.strip()
                                         for t in pr.group(1).split("<")
                                         if t.strip()]

    def suppression_for(self, line: int, rule: str) -> Optional[Suppression]:
        s = self.suppressions.get(line)
        if s is None:
            return None
        if rule in s.rules or "*" in s.rules:
            return s
        return None


# ---------------------------------------------------------------------------
# Lock discovery.
# ---------------------------------------------------------------------------


def last_segment(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def root_segment(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class LockRef:
    key: str                      # canonical name ("TaskDispatcher._lock")
    expr: str                     # source form at the site ("self._lock")
    kind: str                     # "lock" | "rlock" | "cond"
    underlying: Optional["LockRef"] = None   # the lock a Condition wraps


@dataclass
class ClassInfo:
    name: str
    lineno: int
    end_lineno: int
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr -> kind
    cond_aliases: Dict[str, Optional[str]] = field(default_factory=dict)
    guards: Dict[str, str] = field(default_factory=dict)  # attr -> lock expr

    @property
    def primary_lock_attr(self) -> Optional[str]:
        """The lock `*_locked` methods are assumed to hold: `_lock` if
        present, else the class's only non-Condition lock attribute."""
        if "_lock" in self.lock_attrs:
            return "_lock"
        plain = [a for a, k in self.lock_attrs.items() if k != "cond"]
        if len(plain) == 1:
            return plain[0]
        return None

    def lock_ref_for_attr(self, attr: str, owner: str = "self"
                          ) -> Optional[LockRef]:
        kind = self.lock_attrs.get(attr)
        if kind is None:
            return None
        ref = LockRef(key=f"{self.name}.{attr}", expr=f"{owner}.{attr}",
                      kind=kind)
        if kind == "cond":
            under = self.cond_aliases.get(attr)
            if under and under in self.lock_attrs:
                ref.underlying = LockRef(
                    key=f"{self.name}.{under}", expr=f"{owner}.{under}",
                    kind=self.lock_attrs[under])
        return ref


@dataclass
class ModuleModel:
    path: str
    relpath: str
    modname: str
    tree: ast.Module
    directives: Directives
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    module_locks: Dict[str, str] = field(default_factory=dict)  # name -> kind


def _factory_kind(call: ast.AST) -> Optional[str]:
    if not isinstance(call, ast.Call):
        return None
    # asyncio.Lock() / asyncio.Condition() are loop primitives, not
    # thread locks: holding one across an await is the normal idiom.
    if root_segment(call.func) == "asyncio":
        return None
    seg = last_segment(call.func)
    if seg in LOCK_FACTORIES:
        return "lock"
    if seg in RLOCK_FACTORIES:
        return "rlock"
    if seg in COND_FACTORIES:
        return "cond"
    return None


def build_module_model(path: str, relpath: str, source: str,
                       tree: ast.Module) -> ModuleModel:
    modname = os.path.splitext(os.path.basename(path))[0]
    model = ModuleModel(path=path, relpath=relpath, modname=modname,
                        tree=tree, directives=Directives(source))

    # Module-level locks (e.g. rpc.transport._mock_lock).
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            kind = _factory_kind(stmt.value)
            if kind:
                model.module_locks[stmt.targets[0].id] = kind

    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        info = ClassInfo(name=node.name, lineno=node.lineno,
                         end_lineno=node.end_lineno or node.lineno)
        for sub in ast.walk(node):
            target = None
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                target = sub.targets[0]
                value = sub.value
            elif isinstance(sub, ast.AnnAssign):
                target = sub.target
                value = sub.value
            else:
                continue
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            # Guard declaration: a `# guarded by:` comment anywhere in
            # the assignment statement's line span.
            for ln in range(sub.lineno, (sub.end_lineno or sub.lineno) + 1):
                lock_expr = model.directives.guards.get(ln)
                if lock_expr is not None:
                    info.guards[target.attr] = lock_expr
                    break
            kind = _factory_kind(value)
            if kind is None:
                continue
            info.lock_attrs[target.attr] = kind
            if kind == "cond" and isinstance(value, ast.Call) \
                    and value.args:
                arg = value.args[0]
                if isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name) and \
                        arg.value.id == "self":
                    info.cond_aliases[target.attr] = arg.attr
                else:
                    info.cond_aliases[target.attr] = None
            elif kind == "cond":
                # Condition() with no argument owns a private RLock.
                info.cond_aliases[target.attr] = None
        model.classes[node.name] = info
    return model


# ---------------------------------------------------------------------------
# Whole-tree function collection (taint / lifecycle / registry passes).
# ---------------------------------------------------------------------------


@dataclass
class FunctionInfo:
    """One def anywhere in a module (methods, nested classes included),
    with the trust-boundary directives attached to its signature."""

    qualname: str            # "modname.Class.func" / "modname.func"
    name: str                # last segment
    relpath: str
    lineno: int
    params: List[str]
    cls: Optional[str] = None
    sanitizes: Set[str] = field(default_factory=set)
    acquires: Set[str] = field(default_factory=set)
    untrusted: List[str] = field(default_factory=list)
    responders: List[str] = field(default_factory=list)
    loop_only: bool = False
    replicated: List[str] = field(default_factory=list)  # journal ops
    protocol: List[str] = field(default_factory=list)    # ordered steps
    # Filled by the taint summary pass (taint.summarize_function);
    # JSON-serializable so the result cache can persist it.
    taint: Optional[dict] = None
    # Filled by asyncproto.summarize_functions: responder hand-off
    # edges for the global reply-once resolution pass.
    asyncp: Optional[dict] = None
    node: Optional[ast.AST] = None   # not serialized

    def to_dict(self) -> dict:
        return {"qualname": self.qualname, "name": self.name,
                "relpath": self.relpath, "lineno": self.lineno,
                "params": list(self.params), "cls": self.cls,
                "sanitizes": sorted(self.sanitizes),
                "acquires": sorted(self.acquires),
                "untrusted": list(self.untrusted),
                "responders": list(self.responders),
                "loop_only": self.loop_only,
                "replicated": list(self.replicated),
                "protocol": list(self.protocol),
                "taint": self.taint,
                "asyncp": self.asyncp}

    @classmethod
    def from_dict(cls, d: dict) -> "FunctionInfo":
        return cls(qualname=d["qualname"], name=d["name"],
                   relpath=d["relpath"], lineno=d["lineno"],
                   params=list(d["params"]), cls=d.get("cls"),
                   sanitizes=set(d.get("sanitizes", ())),
                   acquires=set(d.get("acquires", ())),
                   untrusted=list(d.get("untrusted", ())),
                   responders=list(d.get("responders", ())),
                   loop_only=bool(d.get("loop_only", False)),
                   replicated=list(d.get("replicated", ())),
                   protocol=list(d.get("protocol", ())),
                   taint=d.get("taint"),
                   asyncp=d.get("asyncp"))


def _signature_lines(node: ast.AST) -> Set[int]:
    """Line numbers where a def's directives may sit: the decorator /
    signature span, plus the line directly above it (long signatures put
    the directive on its own comment line)."""
    start = node.lineno
    for deco in getattr(node, "decorator_list", ()):
        start = min(start, deco.lineno)
    body_start = node.body[0].lineno if node.body else node.lineno + 1
    lines = set(range(start, max(body_start, node.lineno + 1)))
    lines.add(start - 1)
    lines.add(node.lineno)
    return lines


def collect_functions(model: ModuleModel) -> List[FunctionInfo]:
    """Every def in the module, depth-first, with qualified names and
    signature directives resolved.  Unlike iter_functions (which feeds
    the held-lock walk and must not descend), this sees nested defs and
    classes defined inside functions (e.g. HTTP handler classes built
    in a service __init__)."""
    out: List[FunctionInfo] = []
    d = model.directives

    def visit(node: ast.AST, prefix: str, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}"
                a = child.args
                params = [p.arg for p in
                          (a.posonlyargs + a.args + a.kwonlyargs)]
                info = FunctionInfo(
                    qualname=qual, name=child.name, relpath=model.relpath,
                    lineno=child.lineno, params=params, cls=cls,
                    node=child)
                for ln in _signature_lines(child):
                    if ln in d.sanitizes:
                        info.sanitizes |= d.sanitizes[ln]
                    if ln in d.acquires:
                        info.acquires |= d.acquires[ln]
                    if ln in d.untrusted:
                        info.untrusted.extend(
                            s for s in d.untrusted[ln]
                            if s not in info.untrusted)
                    if ln in d.responders:
                        info.responders.extend(
                            s for s in d.responders[ln]
                            if s not in info.responders)
                    if ln in d.loop_only:
                        info.loop_only = True
                    if ln in d.replicated:
                        info.replicated.extend(
                            s for s in d.replicated[ln]
                            if s not in info.replicated)
                    if ln in d.protocol and not info.protocol:
                        info.protocol = list(d.protocol[ln])
                out.append(info)
                visit(child, qual, cls)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}.{child.name}", child.name)
            else:
                visit(child, prefix, cls)

    visit(model.tree, model.modname, None)
    return out


# ---------------------------------------------------------------------------
# Held-lock walk.
# ---------------------------------------------------------------------------


class Hooks:
    """Rule callbacks; override what you need."""

    def on_acquire(self, ref: LockRef, held: List[LockRef],
                   node: ast.AST) -> None:
        pass

    def on_attr(self, node: ast.Attribute, held: List[LockRef]) -> None:
        pass

    def on_call(self, node: ast.Call, held: List[LockRef]) -> None:
        pass

    def on_await(self, node: ast.Await, held: List[LockRef]) -> None:
        pass


class HeldWalker:
    """Walks one function/method tracking statically-held locks."""

    def __init__(self, model: ModuleModel, cls: Optional[ClassInfo],
                 func: ast.AST, hooks: Hooks):
        self.model = model
        self.cls = cls
        self.func = func
        self.hooks = hooks
        self.held: List[LockRef] = []
        self.local_locks: Dict[str, str] = {}   # name -> kind
        self.local_conds: Dict[str, Optional[str]] = {}

    # -- lock resolution ---------------------------------------------------

    def resolve_lock(self, expr: ast.AST) -> Optional[LockRef]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            owner = expr.value.id
            if owner == "self" and self.cls is not None:
                return self.cls.lock_ref_for_attr(expr.attr)
            # cls-style or foreign-object locks are not resolvable.
            return None
        if isinstance(expr, ast.Name):
            name = expr.id
            kind = self.local_locks.get(name)
            if kind:
                fname = getattr(self.func, "name", "<lambda>")
                ref = LockRef(key=f"{self.model.modname}.{fname}.{name}",
                              expr=name, kind=kind)
                if kind == "cond":
                    under = self.local_conds.get(name)
                    if under and under in self.local_locks:
                        ref.underlying = LockRef(
                            key=f"{self.model.modname}.{fname}.{under}",
                            expr=under, kind=self.local_locks[under])
                return ref
            kind = self.model.module_locks.get(name)
            if kind:
                return LockRef(key=f"{self.model.modname}.{name}",
                               expr=name, kind=kind)
        return None

    # -- the walk ----------------------------------------------------------

    def run(self) -> None:
        name = getattr(self.func, "name", "")
        if name.endswith("_locked") and self.cls is not None:
            primary = self.cls.primary_lock_attr
            if primary is not None:
                ref = self.cls.lock_ref_for_attr(primary)
                if ref is not None:
                    self.held.append(ref)
        for stmt in self.func.body:
            self._walk(stmt)

    def _push(self, ref: LockRef) -> List[LockRef]:
        added = [ref]
        self.held.append(ref)
        if ref.underlying is not None:
            self.held.append(ref.underlying)
            added.append(ref.underlying)
        return added

    def _walk(self, node: ast.AST) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            added: List[LockRef] = []
            for item in node.items:
                self._walk(item.context_expr)
                if item.optional_vars is not None:
                    self._walk(item.optional_vars)
                ref = self.resolve_lock(item.context_expr)
                if ref is not None:
                    self.hooks.on_acquire(ref, list(self.held), node)
                    added.extend(self._push(ref))
            for stmt in node.body:
                self._walk(stmt)
            for _ in added:
                self.held.pop()
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested def: helper closures inherit the definition-site
            # held set (see module docstring for the limitation).
            for deco in node.decorator_list:
                self._walk(deco)
            for stmt in node.body:
                self._walk(stmt)
            return
        if isinstance(node, ast.Lambda):
            self._walk(node.body)
            return
        if isinstance(node, ast.ClassDef):
            return  # nested classes: out of scope
        if isinstance(node, ast.Assign):
            if len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                kind = _factory_kind(node.value)
                if kind:
                    name = node.targets[0].id
                    self.local_locks[name] = kind
                    if kind == "cond" and isinstance(node.value, ast.Call) \
                            and node.value.args and \
                            isinstance(node.value.args[0], ast.Name):
                        self.local_conds[name] = node.value.args[0].id
        if isinstance(node, ast.Call):
            self.hooks.on_call(node, list(self.held))
        if isinstance(node, ast.Attribute):
            self.hooks.on_attr(node, list(self.held))
        if isinstance(node, ast.Await):
            self.hooks.on_await(node, list(self.held))
        for child in ast.iter_child_nodes(node):
            self._walk(child)


def iter_functions(model: ModuleModel):
    """Yield (classinfo_or_None, function_node) for every def in the
    module, outermost first.  Nested defs are walked by HeldWalker
    itself (they inherit held state), so only top-level defs and direct
    class methods are yielded."""

    def class_for(node: ast.ClassDef) -> Optional[ClassInfo]:
        return model.classes.get(node.name)

    for stmt in model.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, stmt
        elif isinstance(stmt, ast.ClassDef):
            info = class_for(stmt)
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield info, sub


# ---------------------------------------------------------------------------
# Driver.
# ---------------------------------------------------------------------------


def _collect_py_files(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """(display_relpath, path) pairs.  The display path keeps the
    input directory's own name as its first segment, so scope checks
    (`scheduler/...`, `ops/...`) see the directory structure no matter
    where the tree lives."""
    out: List[Tuple[str, str]] = []
    seen: Set[str] = set()

    def add(rel: str, path: str) -> None:
        ap = os.path.abspath(path)
        if ap not in seen:
            seen.add(ap)
            out.append((rel.replace(os.sep, "/"), path))

    for p in paths:
        if os.path.isfile(p):
            add(os.path.normpath(p), p)
            continue
        base = os.path.basename(os.path.normpath(p))
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for f in sorted(filenames):
                if f.endswith(".py"):
                    full = os.path.join(dirpath, f)
                    add(os.path.join(base, os.path.relpath(full, p)),
                        full)
    return out


@dataclass
class _FileRecord:
    relpath: str
    path: str
    source: str
    content_hash: str
    model: Optional[ModuleModel] = None        # parsed lazily / on miss
    functions: List[FunctionInfo] = field(default_factory=list)
    callsites: List[dict] = field(default_factory=list)
    local_findings: Optional[List[Finding]] = None
    from_cache: bool = False


def _collect_callsites(model: ModuleModel) -> List[dict]:
    """Flat record of every call with keyword arguments plus the
    TaskType registrations — enough for the wire-compat unknown-field
    check and the taint-registry check to run without the AST (so a
    cache hit skips parsing entirely)."""
    sites: List[dict] = []
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        last = last_segment(node.func)
        if last is None:
            continue
        kwargs = [kw.arg for kw in node.keywords if kw.arg]
        chain: List[str] = []
        f = node.func
        while isinstance(f, ast.Attribute):
            chain.append(f.attr)
            f = f.value
        if isinstance(f, ast.Name):
            chain.append(f.id)
        chain.reverse()
        if last == "TaskType" and kwargs:
            kind = None
            factories: List[str] = []
            for kw in node.keywords:
                if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                    kind = kw.value.value
                if kw.arg == "make_task":
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Call):
                            seg = last_segment(sub.func)
                            if seg:
                                factories.append(seg)
                        elif isinstance(sub, ast.Name):
                            factories.append(sub.id)
            lam_params: Set[str] = set()
            for kw in node.keywords:
                if kw.arg == "make_task" and \
                        isinstance(kw.value, ast.Lambda):
                    lam_params = {a.arg for a in kw.value.args.args}
            factories = [n for n in factories
                         if n not in lam_params and n != "TaskType"]
            sites.append({"tasktype": True, "kind": kind,
                          "factories": factories, "line": node.lineno})
        if kwargs:
            sites.append({"last": last, "chain": chain,
                          "kwargs": kwargs, "line": node.lineno})
    return sites


_DEF_NAME_RE = re.compile(r"^\s*(?:async\s+)?def\s+(\w+)")


def scan_directives(sources: Dict[str, str]
                    ) -> Tuple[str, Dict[str, Set[str]], Set[str], Set[str]]:
    """Regex pre-pass over raw sources (no parsing): returns
    (fingerprint, sanitizer map, acquires name set, loop-only name set).

    Per-file analysis results depend on which *names* carry sanitizes/
    acquires/untrusted annotations anywhere in the tree (the taint pass
    resolves sanitizer calls by name across modules), so the result
    cache keys on this fingerprint alongside each file's content hash —
    retargeting an annotation invalidates everything, cheaply detected
    before any AST work."""
    import hashlib

    entries: List[Tuple[str, int, str, str]] = []
    sanitizers: Dict[str, Set[str]] = {}
    acquires: Set[str] = set()
    loop_only: Set[str] = set()
    for rel in sorted(sources):
        lines = sources[rel].splitlines()
        for i, text in enumerate(lines):
            if "ytpu:" not in text:
                continue
            hit = None
            for regex, kind in ((_SANITIZES_RE, "sanitizes"),
                                (_ACQUIRES_RE, "acquires"),
                                (_UNTRUSTED_RE, "untrusted"),
                                (_RESPONDER_RE, "responder"),
                                (_REPLICATED_RE, "replicated"),
                                (_PROTOCOL_RE, "protocol")):
                m = regex.search(text)
                if m:
                    hit = (kind, m.group(1))
                    break
            if hit is None and _LOOP_ONLY_RE.search(text):
                hit = ("loop-only", "")
            if hit is None:
                continue
            # Associate with the owning def: same line; a pure-comment
            # line binds to the def below (above-decorator style); a
            # trailing comment on a signature continuation line binds
            # to the def above.
            defname = ""
            dm = _DEF_NAME_RE.match(text)
            if dm:
                defname = dm.group(1)
            elif text.lstrip().startswith("#"):
                for j in range(i + 1, min(i + 9, len(lines))):
                    dm = _DEF_NAME_RE.match(lines[j])
                    if dm:
                        defname = dm.group(1)
                        break
            else:
                for j in range(i - 1, max(i - 9, -1), -1):
                    dm = _DEF_NAME_RE.match(lines[j])
                    if dm:
                        defname = dm.group(1)
                        break
            entries.append((rel, i + 1, defname, f"{hit[0]}({hit[1]})"))
            tags = {t.strip() for t in hit[1].split(",") if t.strip()}
            if defname and hit[0] == "sanitizes":
                sanitizers.setdefault(defname, set()).update(tags)
            elif defname and hit[0] == "acquires":
                acquires.add(defname)
            elif defname and hit[0] == "loop-only":
                loop_only.add(defname)
    fp = hashlib.sha256(repr(entries).encode()).hexdigest()
    return fp, sanitizers, acquires, loop_only


def analyze_paths(paths: Sequence[str],
                  config: Optional[AnalyzerConfig] = None,
                  cache=None,
                  ) -> Tuple[List[Finding], dict]:
    """Run every rule family over the given files/directories.

    Returns (findings, stats).  Findings matched by a
    ``# ytpu: allow(<rule>)  # reason`` comment on their line come back
    with ``suppressed=True``; a suppression without a reason adds a
    ``suppression`` finding of its own.  The process exit decision
    belongs to the caller (__main__): unsuppressed findings fail.

    ``cache`` is an optional analysis.cache.ResultCache: per-file parse
    + rule results are reused when the file's content hash, the global
    directive digest and the analyzer fingerprint all match.
    """
    import hashlib
    import time as _time

    from . import (asyncproto, device_sync, jit_hygiene, lifecycle,
                   lockrules, replproto, taint, wirecompat)

    config = config or AnalyzerConfig()
    files = _collect_py_files(paths)
    findings: List[Finding] = []
    timings: Dict[str, float] = {}
    records: List[_FileRecord] = []
    cache_hits = 0

    def _timed(name: str, fn, *args):
        t0 = _time.perf_counter()
        out = fn(*args)
        timings[name] = timings.get(name, 0.0) + _time.perf_counter() - t0
        return out

    # -- phase 0: read sources, directive pre-pass -------------------------
    t0 = _time.perf_counter()
    sources: Dict[str, str] = {}
    by_rel: Dict[str, Tuple[str, str]] = {}
    for rel, path in files:
        try:
            with open(path, "r", encoding="utf-8") as fp:
                sources[rel] = fp.read()
            by_rel[rel] = (rel, path)
        except OSError as e:
            findings.append(Finding("parse-error", rel, 1, str(e)))
    directive_fp, sanitizer_map, acquires_names, loop_only_names = \
        scan_directives(sources)
    cfg_fp = hashlib.sha256(
        repr(sorted(config.digest_fields().items())).encode()).hexdigest()
    global_key = hashlib.sha256(
        (directive_fp + cfg_fp).encode()).hexdigest()

    # -- phase 1: per-file analysis (cache-keyed on content + globals) -----
    # Cache lookups, parsing and the mutating summary passes stay
    # serial (parse errors land deterministically and the summaries
    # write into the shared FunctionInfo records); the read-only rule
    # families then fan out on a thread pool, ONE WORKER PER FAMILY,
    # each sweeping every cold file.  The content-hash cache is
    # unchanged: a cache hit removes the file from every family's
    # sweep, and per-family wall times land in stats["timings"].
    cold: List[_FileRecord] = []
    for rel, path in files:
        if rel not in sources:
            continue
        source = sources[rel]
        rec = _FileRecord(
            relpath=rel, path=path, source=source,
            content_hash=hashlib.sha256(source.encode()).hexdigest())
        entry = (cache.get(rec.content_hash, global_key)
                 if cache is not None else None)
        if entry is not None:
            rec.functions = [FunctionInfo.from_dict(d)
                             for d in entry.get("functions", ())]
            rec.callsites = list(entry.get("callsites", ()))
            rec.local_findings = [Finding(**d)
                                  for d in entry.get("findings", ())]
            rec.from_cache = True
            cache_hits += 1
        else:
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError as e:
                findings.append(Finding("parse-error", rel, 1, str(e)))
                continue
            rec.model = build_module_model(path, rel, source, tree)
            rec.functions = collect_functions(rec.model)
            _timed("taint", taint.summarize_functions,
                   rec.model, rec.functions, sanitizer_map)
            _timed("asyncproto", asyncproto.summarize_functions,
                   rec.model, rec.functions)
            rec.callsites = _collect_callsites(rec.model)
            cold.append(rec)
        records.append(rec)

    families = (
        ("lockrules",
         lambda r: lockrules.check_module(r.model, config)),
        ("jit-hygiene",
         lambda r: jit_hygiene.check_module(r.model, config)),
        ("device-sync",
         lambda r: device_sync.check_module(r.model, config)),
        ("lifecycle",
         lambda r: lifecycle.check_module(r.model, config,
                                          acquires_names)),
        ("asyncproto",
         lambda r: asyncproto.check_module(r.model, r.functions, config,
                                           loop_only_names)),
        ("replproto",
         lambda r: replproto.check_module(r.model, r.functions, config)),
    )

    def _family_sweep(name, fn):
        f0 = _time.perf_counter()
        out = {rec.relpath: fn(rec) for rec in cold}
        return name, out, _time.perf_counter() - f0

    if cold:
        from concurrent.futures import ThreadPoolExecutor
        workers = min(len(families), max(2, os.cpu_count() or 2))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            swept = list(pool.map(lambda nf: _family_sweep(*nf),
                                  families))
        for name, _, secs in swept:
            timings[name] = timings.get(name, 0.0) + secs
        by_family = {name: out for name, out, _ in swept}
        for rec in cold:
            raw = [f for name, _ in families
                   for f in by_family[name].get(rec.relpath, ())]
            rec.local_findings = raw
            if cache is not None:
                cache.put(rec.content_hash, global_key, {
                    "functions": [i.to_dict() for i in rec.functions],
                    "callsites": rec.callsites,
                    "findings": [{"rule": f.rule, "path": f.path,
                                  "line": f.line, "message": f.message}
                                 for f in raw],
                })
    timings["per-file-total"] = _time.perf_counter() - t0

    all_functions: List[FunctionInfo] = []
    for rec in records:
        all_functions.extend(rec.functions)

    # -- phase 2: global passes --------------------------------------------
    tasktype_sites = [dict(s, relpath=rec.relpath)
                      for rec in records for s in rec.callsites
                      if s.get("tasktype")]
    raw_global: List[Finding] = []
    raw_global.extend(_timed(
        "taint", taint.check_global, all_functions, tasktype_sites,
        sanitizer_map))
    raw_global.extend(_timed(
        "wire-compat", wirecompat.check_paths, paths, records, config))
    raw_global.extend(_timed(
        "asyncproto", asyncproto.check_global, all_functions, config))

    # -- suppression pass --------------------------------------------------
    directives_by_rel: Dict[str, Directives] = {}

    def _directives(rel: str) -> Optional[Directives]:
        if rel not in directives_by_rel:
            rec = next((r for r in records if r.relpath == rel), None)
            if rec is None:
                return None
            if rec.model is not None:
                directives_by_rel[rel] = rec.model.directives
            else:
                directives_by_rel[rel] = Directives(rec.source)
        return directives_by_rel[rel]

    seen_keys: Set[Tuple[str, str, int, str]] = set()
    for f in [f for rec in records for f in (rec.local_findings or [])] \
            + raw_global:
        key = (f.rule, f.path, f.line, f.message)
        if key in seen_keys:
            continue
        seen_keys.add(key)
        d = _directives(f.path)
        if d is not None:
            s = d.suppression_for(f.line, f.rule)
            if s is not None:
                s.used = True
                f.suppressed = True
        findings.append(f)
    for rec in records:
        d = _directives(rec.relpath)
        if d is None:
            continue
        for s in d.suppressions.values():
            unknown = s.rules - set(RULES) - {"*"}
            if unknown:
                findings.append(Finding(
                    "suppression", rec.relpath, s.line,
                    f"unknown rule id(s) in suppression: "
                    f"{', '.join(sorted(unknown))}"))
            if not s.reason:
                findings.append(Finding(
                    "suppression", rec.relpath, s.line,
                    "suppression without a written reason "
                    "(# ytpu: allow(<rule>)  # why it is safe)"))
            elif config.strict_suppressions and not s.used:
                findings.append(Finding(
                    "suppression", rec.relpath, s.line,
                    "suppression matched no finding"))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    stats = {
        "files_analyzed": len(records),
        "findings": sum(1 for f in findings if not f.suppressed),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "cache_hits": cache_hits,
        "timings": {k: round(v, 4) for k, v in sorted(timings.items())},
    }
    return findings, stats
