"""Analyzer infrastructure: directives, lock discovery, held-lock walk.

Everything here is shared by the rule families (lockrules.py,
jit_hygiene.py): parsing ``# guarded by:`` / ``# ytpu: allow(...)``
comments, discovering which attributes of a class are locks (and which
Conditions wrap which locks), and walking a function body while
tracking the set of locks statically known to be held.

Scope and honesty notes (also in doc/static_analysis.md):

* The walk is intraprocedural.  ``*_locked`` methods are assumed to
  run with their class's *primary* lock held (``self._lock`` when the
  class has one, else its only lock attribute) — that is exactly the
  convention the suffix declares.  Cross-class and cross-function
  acquisition chains are the runtime locktrace's job.
* A nested ``def`` inherits the held set of its definition site.  For
  the synchronous helper-closure idiom this is right; a closure stashed
  and called later from another thread is invisible to this pass.
* Lock acquisition is recognized on ``with`` statements only.  Raw
  ``.acquire()``/``.release()`` pairs (the locktrace proxy internals)
  are not tracked.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULES: Dict[str, str] = {
    "guarded-by": "guarded attribute accessed without its declared lock",
    "locked-call": "*_locked method called from a site not holding the lock",
    "lock-order": "nested lock acquisition undeclared or inverting the "
                  "declared hierarchy (analysis/lock_hierarchy.toml)",
    "block-under-lock": "blocking call (sleep / I/O / RPC / device sync) "
                        "inside a lock body on a scheduler/daemon hot path",
    "jit-nondet": "wall-clock or nondeterminism call inside a @jax.jit "
                  "function",
    "jit-tracer-if": "Python branch on a traced argument inside a "
                     "@jax.jit function",
    "jit-static-unhashable": "unhashable value bound to a static jit "
                             "argument",
    "suppression": "malformed suppression or suppression without a "
                   "written reason",
    "parse-error": "file could not be parsed",
}

# Factories whose call result is a lock / a condition.  Matched on the
# last dotted segment so `threading.Lock`, bare `Lock` (from-import) and
# locktrace's `_real_lock` all register.
LOCK_FACTORIES = {"Lock", "allocate_lock", "_real_lock"}
RLOCK_FACTORIES = {"RLock", "_real_rlock"}
COND_FACTORIES = {"Condition"}

# Methods in which unguarded access to guarded attributes is legal: the
# object is not yet (or no longer) shared.
CONSTRUCTION_METHODS = {"__init__", "__new__", "__post_init__", "__del__"}

_SUPPRESS_RE = re.compile(
    r"#\s*ytpu:\s*allow\(\s*([A-Za-z0-9_*,\- ]*)\s*\)\s*(.*)$")
_GUARD_RE = re.compile(r"#\s*guarded by:\s*([A-Za-z0-9_.\[\]'\"]+)\s*$")


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "suppressed": self.suppressed}


@dataclass
class Suppression:
    line: int
    rules: Set[str]
    reason: str
    used: bool = False


@dataclass
class AnalyzerConfig:
    # Path fragments selecting the modules where block-under-lock
    # applies (grant/compile hot paths; the cache server's disk engine
    # legitimately does I/O under its own lock and stays out).
    hot_path_fragments: Tuple[str, ...] = ("scheduler", "daemon")
    # Path fragments selecting the modules where jit hygiene applies.
    jit_path_fragments: Tuple[str, ...] = ("ops", "parallel")
    # Lock hierarchy: canonical lock name -> rank (lower acquired
    # first).  Loaded from lock_hierarchy.toml by the CLI.
    lock_ranks: Dict[str, int] = field(default_factory=dict)
    # Report suppressions that matched nothing (kept off the CI default:
    # rule evolution must not turn a stale-but-documented allow into a
    # gate failure).
    strict_suppressions: bool = False


# ---------------------------------------------------------------------------
# Directives (comment-level annotations).
# ---------------------------------------------------------------------------


class Directives:
    """Per-file suppressions and guard declarations, by line number.

    Guard comments are associated with an attribute by
    build_module_model, which matches them against the line span of the
    ``self.X = ...`` statement they sit on (so the comment may ride the
    closing line of a multi-line assignment)."""

    def __init__(self, source: str):
        self.suppressions: Dict[int, Suppression] = {}
        self.guards: Dict[int, str] = {}   # lineno -> lock expr
        for lineno, text in enumerate(source.splitlines(), start=1):
            if "#" not in text:
                continue
            m = _SUPPRESS_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                reason = m.group(2).strip().lstrip("#").strip()
                self.suppressions[lineno] = Suppression(
                    lineno, rules, reason)
            g = _GUARD_RE.search(text)
            if g:
                self.guards[lineno] = g.group(1)

    def suppression_for(self, line: int, rule: str) -> Optional[Suppression]:
        s = self.suppressions.get(line)
        if s is None:
            return None
        if rule in s.rules or "*" in s.rules:
            return s
        return None


# ---------------------------------------------------------------------------
# Lock discovery.
# ---------------------------------------------------------------------------


def last_segment(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def root_segment(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class LockRef:
    key: str                      # canonical name ("TaskDispatcher._lock")
    expr: str                     # source form at the site ("self._lock")
    kind: str                     # "lock" | "rlock" | "cond"
    underlying: Optional["LockRef"] = None   # the lock a Condition wraps


@dataclass
class ClassInfo:
    name: str
    lineno: int
    end_lineno: int
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr -> kind
    cond_aliases: Dict[str, Optional[str]] = field(default_factory=dict)
    guards: Dict[str, str] = field(default_factory=dict)  # attr -> lock expr

    @property
    def primary_lock_attr(self) -> Optional[str]:
        """The lock `*_locked` methods are assumed to hold: `_lock` if
        present, else the class's only non-Condition lock attribute."""
        if "_lock" in self.lock_attrs:
            return "_lock"
        plain = [a for a, k in self.lock_attrs.items() if k != "cond"]
        if len(plain) == 1:
            return plain[0]
        return None

    def lock_ref_for_attr(self, attr: str, owner: str = "self"
                          ) -> Optional[LockRef]:
        kind = self.lock_attrs.get(attr)
        if kind is None:
            return None
        ref = LockRef(key=f"{self.name}.{attr}", expr=f"{owner}.{attr}",
                      kind=kind)
        if kind == "cond":
            under = self.cond_aliases.get(attr)
            if under and under in self.lock_attrs:
                ref.underlying = LockRef(
                    key=f"{self.name}.{under}", expr=f"{owner}.{under}",
                    kind=self.lock_attrs[under])
        return ref


@dataclass
class ModuleModel:
    path: str
    relpath: str
    modname: str
    tree: ast.Module
    directives: Directives
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    module_locks: Dict[str, str] = field(default_factory=dict)  # name -> kind


def _factory_kind(call: ast.AST) -> Optional[str]:
    if not isinstance(call, ast.Call):
        return None
    seg = last_segment(call.func)
    if seg in LOCK_FACTORIES:
        return "lock"
    if seg in RLOCK_FACTORIES:
        return "rlock"
    if seg in COND_FACTORIES:
        return "cond"
    return None


def build_module_model(path: str, relpath: str, source: str,
                       tree: ast.Module) -> ModuleModel:
    modname = os.path.splitext(os.path.basename(path))[0]
    model = ModuleModel(path=path, relpath=relpath, modname=modname,
                        tree=tree, directives=Directives(source))

    # Module-level locks (e.g. rpc.transport._mock_lock).
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            kind = _factory_kind(stmt.value)
            if kind:
                model.module_locks[stmt.targets[0].id] = kind

    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        info = ClassInfo(name=node.name, lineno=node.lineno,
                         end_lineno=node.end_lineno or node.lineno)
        for sub in ast.walk(node):
            target = None
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                target = sub.targets[0]
                value = sub.value
            elif isinstance(sub, ast.AnnAssign):
                target = sub.target
                value = sub.value
            else:
                continue
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            # Guard declaration: a `# guarded by:` comment anywhere in
            # the assignment statement's line span.
            for ln in range(sub.lineno, (sub.end_lineno or sub.lineno) + 1):
                lock_expr = model.directives.guards.get(ln)
                if lock_expr is not None:
                    info.guards[target.attr] = lock_expr
                    break
            kind = _factory_kind(value)
            if kind is None:
                continue
            info.lock_attrs[target.attr] = kind
            if kind == "cond" and isinstance(value, ast.Call) \
                    and value.args:
                arg = value.args[0]
                if isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name) and \
                        arg.value.id == "self":
                    info.cond_aliases[target.attr] = arg.attr
                else:
                    info.cond_aliases[target.attr] = None
            elif kind == "cond":
                # Condition() with no argument owns a private RLock.
                info.cond_aliases[target.attr] = None
        model.classes[node.name] = info
    return model


# ---------------------------------------------------------------------------
# Held-lock walk.
# ---------------------------------------------------------------------------


class Hooks:
    """Rule callbacks; override what you need."""

    def on_acquire(self, ref: LockRef, held: List[LockRef],
                   node: ast.AST) -> None:
        pass

    def on_attr(self, node: ast.Attribute, held: List[LockRef]) -> None:
        pass

    def on_call(self, node: ast.Call, held: List[LockRef]) -> None:
        pass


class HeldWalker:
    """Walks one function/method tracking statically-held locks."""

    def __init__(self, model: ModuleModel, cls: Optional[ClassInfo],
                 func: ast.AST, hooks: Hooks):
        self.model = model
        self.cls = cls
        self.func = func
        self.hooks = hooks
        self.held: List[LockRef] = []
        self.local_locks: Dict[str, str] = {}   # name -> kind
        self.local_conds: Dict[str, Optional[str]] = {}

    # -- lock resolution ---------------------------------------------------

    def resolve_lock(self, expr: ast.AST) -> Optional[LockRef]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            owner = expr.value.id
            if owner == "self" and self.cls is not None:
                return self.cls.lock_ref_for_attr(expr.attr)
            # cls-style or foreign-object locks are not resolvable.
            return None
        if isinstance(expr, ast.Name):
            name = expr.id
            kind = self.local_locks.get(name)
            if kind:
                fname = getattr(self.func, "name", "<lambda>")
                ref = LockRef(key=f"{self.model.modname}.{fname}.{name}",
                              expr=name, kind=kind)
                if kind == "cond":
                    under = self.local_conds.get(name)
                    if under and under in self.local_locks:
                        ref.underlying = LockRef(
                            key=f"{self.model.modname}.{fname}.{under}",
                            expr=under, kind=self.local_locks[under])
                return ref
            kind = self.model.module_locks.get(name)
            if kind:
                return LockRef(key=f"{self.model.modname}.{name}",
                               expr=name, kind=kind)
        return None

    # -- the walk ----------------------------------------------------------

    def run(self) -> None:
        name = getattr(self.func, "name", "")
        if name.endswith("_locked") and self.cls is not None:
            primary = self.cls.primary_lock_attr
            if primary is not None:
                ref = self.cls.lock_ref_for_attr(primary)
                if ref is not None:
                    self.held.append(ref)
        for stmt in self.func.body:
            self._walk(stmt)

    def _push(self, ref: LockRef) -> List[LockRef]:
        added = [ref]
        self.held.append(ref)
        if ref.underlying is not None:
            self.held.append(ref.underlying)
            added.append(ref.underlying)
        return added

    def _walk(self, node: ast.AST) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            added: List[LockRef] = []
            for item in node.items:
                self._walk(item.context_expr)
                if item.optional_vars is not None:
                    self._walk(item.optional_vars)
                ref = self.resolve_lock(item.context_expr)
                if ref is not None:
                    self.hooks.on_acquire(ref, list(self.held), node)
                    added.extend(self._push(ref))
            for stmt in node.body:
                self._walk(stmt)
            for _ in added:
                self.held.pop()
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested def: helper closures inherit the definition-site
            # held set (see module docstring for the limitation).
            for deco in node.decorator_list:
                self._walk(deco)
            for stmt in node.body:
                self._walk(stmt)
            return
        if isinstance(node, ast.Lambda):
            self._walk(node.body)
            return
        if isinstance(node, ast.ClassDef):
            return  # nested classes: out of scope
        if isinstance(node, ast.Assign):
            if len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                kind = _factory_kind(node.value)
                if kind:
                    name = node.targets[0].id
                    self.local_locks[name] = kind
                    if kind == "cond" and isinstance(node.value, ast.Call) \
                            and node.value.args and \
                            isinstance(node.value.args[0], ast.Name):
                        self.local_conds[name] = node.value.args[0].id
        if isinstance(node, ast.Call):
            self.hooks.on_call(node, list(self.held))
        if isinstance(node, ast.Attribute):
            self.hooks.on_attr(node, list(self.held))
        for child in ast.iter_child_nodes(node):
            self._walk(child)


def iter_functions(model: ModuleModel):
    """Yield (classinfo_or_None, function_node) for every def in the
    module, outermost first.  Nested defs are walked by HeldWalker
    itself (they inherit held state), so only top-level defs and direct
    class methods are yielded."""

    def class_for(node: ast.ClassDef) -> Optional[ClassInfo]:
        return model.classes.get(node.name)

    for stmt in model.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, stmt
        elif isinstance(stmt, ast.ClassDef):
            info = class_for(stmt)
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield info, sub


# ---------------------------------------------------------------------------
# Driver.
# ---------------------------------------------------------------------------


def _collect_py_files(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """(display_relpath, path) pairs.  The display path keeps the
    input directory's own name as its first segment, so scope checks
    (`scheduler/...`, `ops/...`) see the directory structure no matter
    where the tree lives."""
    out: List[Tuple[str, str]] = []
    seen: Set[str] = set()

    def add(rel: str, path: str) -> None:
        ap = os.path.abspath(path)
        if ap not in seen:
            seen.add(ap)
            out.append((rel.replace(os.sep, "/"), path))

    for p in paths:
        if os.path.isfile(p):
            add(os.path.normpath(p), p)
            continue
        base = os.path.basename(os.path.normpath(p))
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for f in sorted(filenames):
                if f.endswith(".py"):
                    full = os.path.join(dirpath, f)
                    add(os.path.join(base, os.path.relpath(full, p)),
                        full)
    return out


def analyze_paths(paths: Sequence[str],
                  config: Optional[AnalyzerConfig] = None
                  ) -> Tuple[List[Finding], dict]:
    """Run every rule family over the given files/directories.

    Returns (findings, stats).  Findings matched by a
    ``# ytpu: allow(<rule>)  # reason`` comment on their line come back
    with ``suppressed=True``; a suppression without a reason adds a
    ``suppression`` finding of its own.  The process exit decision
    belongs to the caller (__main__): unsuppressed findings fail.
    """
    from . import jit_hygiene, lockrules

    config = config or AnalyzerConfig()
    files = _collect_py_files(paths)
    findings: List[Finding] = []
    analyzed = 0
    for rel, path in files:
        try:
            with open(path, "r", encoding="utf-8") as fp:
                source = fp.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            findings.append(Finding("parse-error", rel, 1, str(e)))
            continue
        analyzed += 1
        model = build_module_model(path, rel, source, tree)
        raw: List[Finding] = []
        raw.extend(lockrules.check_module(model, config))
        raw.extend(jit_hygiene.check_module(model, config))
        # Suppression pass.
        for f in raw:
            s = model.directives.suppression_for(f.line, f.rule)
            if s is not None:
                s.used = True
                f.suppressed = True
            findings.append(f)
        for s in model.directives.suppressions.values():
            unknown = s.rules - set(RULES) - {"*"}
            if unknown:
                findings.append(Finding(
                    "suppression", rel, s.line,
                    f"unknown rule id(s) in suppression: "
                    f"{', '.join(sorted(unknown))}"))
            if not s.reason:
                findings.append(Finding(
                    "suppression", rel, s.line,
                    "suppression without a written reason "
                    "(# ytpu: allow(<rule>)  # why it is safe)"))
            elif config.strict_suppressions and not s.used:
                findings.append(Finding(
                    "suppression", rel, s.line,
                    "suppression matched no finding"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    stats = {
        "files_analyzed": analyzed,
        "findings": sum(1 for f in findings if not f.suppressed),
        "suppressed": sum(1 for f in findings if f.suppressed),
    }
    return findings, stats
