"""Rule families 1-3: guarded-by, lock-order, block-under-lock.

All three share one held-lock walk per function (core.HeldWalker); each
family is a Hooks callback recording findings.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (
    CONSTRUCTION_METHODS,
    AnalyzerConfig,
    ClassInfo,
    Finding,
    HeldWalker,
    Hooks,
    LockRef,
    ModuleModel,
    _dotted,
    iter_functions,
    last_segment,
    root_segment,
)

# ---------------------------------------------------------------------------
# block-under-lock matchers.
# ---------------------------------------------------------------------------

# Matched on the call's last dotted segment.
_BLOCKING_LAST_SEG: Dict[str, str] = {
    "sleep": "sleep",
    "open": "file I/O",
    "urlopen": "network I/O",
    "communicate": "subprocess wait",
    "accept": "socket I/O",
    "recv": "socket I/O",
    "recvfrom": "socket I/O",
    "sendall": "socket I/O",
    "connect": "socket I/O",
    "select": "blocking select",
    "call": "RPC call",
    "block_until_ready": "device sync",
    "device_get": "device transfer",
    "device_put": "device transfer",
    # /proc samplers (daemon/sysinfo.py) and their injection points:
    # their contract is file I/O however cheap it looks at the call site.
    "_memory_reader": "/proc sampling I/O",
    "read_memory_available": "/proc sampling I/O",
    "read_memory_total": "/proc sampling I/O",
    "read_cgroup_present": "/proc sampling I/O",
    "_read_proc_stat": "/proc sampling I/O",
}

# Matched on the call's root segment (module-style prefixes).
_BLOCKING_ROOT: Dict[str, str] = {
    "jnp": "device dispatch",
    "jax": "device dispatch",
    "subprocess": "subprocess",
    "socket": "socket I/O",
    "requests": "network I/O",
    "urllib": "network I/O",
}


def _in_scope(relpath: str, fragments: Tuple[str, ...]) -> bool:
    parts = relpath.replace("\\", "/").split("/")
    return any(frag in parts for frag in fragments)


class _GuardedByHooks(Hooks):
    def __init__(self, model: ModuleModel, cls: Optional[ClassInfo],
                 func: ast.AST, findings: List[Finding]):
        self.model = model
        self.cls = cls
        self.func = func
        self.findings = findings
        name = getattr(func, "name", "")
        self.exempt = name in CONSTRUCTION_METHODS

    def _holds(self, held: List[LockRef], lock_expr: str) -> bool:
        return any(h.expr == lock_expr for h in held)

    def on_attr(self, node: ast.Attribute, held: List[LockRef]) -> None:
        if self.exempt or self.cls is None:
            return
        if not (isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return
        required = self.cls.guards.get(node.attr)
        if required is None:
            return
        if self._holds(held, required):
            return
        self.findings.append(Finding(
            "guarded-by", self.model.relpath, node.lineno,
            f"self.{node.attr} is declared guarded by {required} but "
            f"accessed in {self.cls.name}."
            f"{getattr(self.func, 'name', '?')} without it held"))

    def on_call(self, node: ast.Call, held: List[LockRef]) -> None:
        if self.exempt or self.cls is None:
            return
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and func.attr.endswith("_locked")):
            return
        primary = self.cls.primary_lock_attr
        if primary is None:
            return
        if self._holds(held, f"self.{primary}"):
            return
        self.findings.append(Finding(
            "locked-call", self.model.relpath, node.lineno,
            f"self.{func.attr}() requires self.{primary} held "
            f"(callers must hold the lock the *_locked suffix declares)"))


class _LockOrderHooks(Hooks):
    def __init__(self, model: ModuleModel,
                 findings: List[Finding],
                 edges: List[Tuple[str, str, str, int]]):
        self.model = model
        self.findings = findings
        self.edges = edges

    @staticmethod
    def _order_key(ref: LockRef) -> Optional[str]:
        # Acquiring a Condition acquires its underlying lock; ordering
        # is defined on real locks.  A Condition over an unknown lock
        # contributes no edge.
        if ref.kind == "cond":
            return ref.underlying.key if ref.underlying else None
        return ref.key

    def on_acquire(self, ref: LockRef, held: List[LockRef],
                   node: ast.AST) -> None:
        new_key = self._order_key(ref)
        if new_key is None:
            return
        held_keys = []
        for h in held:
            k = self._order_key(h)
            if k is not None and k not in held_keys:
                held_keys.append(k)
        if new_key in held_keys and ref.kind == "lock":
            self.findings.append(Finding(
                "lock-order", self.model.relpath, node.lineno,
                f"{ref.expr} is a non-reentrant Lock already held here "
                f"(self-deadlock)"))
            return
        site = f"{self.model.relpath}:{node.lineno}"
        for prev in held_keys:
            if prev != new_key:
                self.edges.append((prev, new_key, site, node.lineno))


class _BlockUnderLockHooks(Hooks):
    def __init__(self, model: ModuleModel, cls: Optional[ClassInfo],
                 findings: List[Finding]):
        self.model = model
        self.cls = cls
        self.findings = findings

    def _wait_exempt(self, recv: ast.AST, held: List[LockRef]) -> bool:
        """cv.wait() releases the lock while parked: waiting on a
        Condition (or on the held lock object itself) is the one legal
        blocking call under a lock."""
        recv_str = _dotted(recv)
        if recv_str is None:
            return False
        for h in held:
            if h.expr == recv_str:
                return True
            if h.underlying is not None and h.underlying.expr == recv_str:
                return True
        if self.cls is not None and isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self" \
                and self.cls.lock_attrs.get(recv.attr) == "cond":
            under = self.cls.cond_aliases.get(recv.attr)
            if under is not None and any(
                    h.expr == f"self.{under}" for h in held):
                return True
        return False

    def on_call(self, node: ast.Call, held: List[LockRef]) -> None:
        if not held:
            return
        func = node.func
        seg = last_segment(func)
        root = root_segment(func)
        held_desc = ", ".join(sorted({h.expr for h in held}))
        if seg == "wait":
            if isinstance(func, ast.Attribute) and \
                    self._wait_exempt(func.value, held):
                return
            self.findings.append(Finding(
                "block-under-lock", self.model.relpath, node.lineno,
                f"blocking wait under lock ({held_desc}): only a "
                f"Condition over the held lock may wait here"))
            return
        if seg == "join" and isinstance(func, ast.Attribute):
            recv = _dotted(func.value) or ""
            if "thread" in recv.lower() or "proc" in recv.lower():
                self.findings.append(Finding(
                    "block-under-lock", self.model.relpath, node.lineno,
                    f"thread join under lock ({held_desc})"))
            return
        what = None
        if seg in _BLOCKING_LAST_SEG:
            what = _BLOCKING_LAST_SEG[seg]
        elif root in _BLOCKING_ROOT and root != seg:
            what = _BLOCKING_ROOT[root]
        if what is None:
            return
        self.findings.append(Finding(
            "block-under-lock", self.model.relpath, node.lineno,
            f"{what} ({_dotted(func) or seg}) inside a lock body "
            f"({held_desc}) on a hot path"))


class _AioBlockingVisitor(ast.NodeVisitor):
    """aio-blocking: blocking calls inside ``async def`` coroutines in
    the event-loop front end's scope (rpc/).  A sleep, file/socket I/O
    or sync RPC ``.call`` on the loop silently regresses every
    connection it serves to the thread-per-connection latency profile
    the front end replaced — so it is a finding, same suppression
    policy as every other rule.

    Awaited calls are exempt (``await asyncio.sleep`` / stream I/O —
    a *blocking* call is not awaitable, so awaiting one would already
    be a runtime error), as is anything rooted at ``asyncio`` and the
    executor hand-off itself (``run_in_executor`` receives a function
    reference, not a call).  The check still descends into an awaited
    call's ARGUMENTS: ``await send(sock.recv(1))`` hides a blocking
    recv in plain sight."""

    def __init__(self, model: ModuleModel, fn: ast.AsyncFunctionDef,
                 findings: List[Finding]):
        self.model = model
        self.fn = fn
        self.findings = findings

    def visit_Await(self, node: ast.Await) -> None:
        value = node.value
        if isinstance(value, ast.Call):
            # The awaited call itself is exempt; its arguments are not.
            for arg in value.args:
                self.visit(arg)
            for kw in value.keywords:
                self.visit(kw.value)
            self.visit(value.func)
        else:
            self.visit(value)

    def visit_FunctionDef(self, node) -> None:
        pass  # a nested sync def runs wherever it is called, not here

    def visit_AsyncFunctionDef(self, node) -> None:
        pass  # visited in its own right by check_module's walk

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        func = node.func
        seg = last_segment(func)
        root = root_segment(func)
        if root == "asyncio":
            return
        what = None
        if seg in _BLOCKING_LAST_SEG:
            what = _BLOCKING_LAST_SEG[seg]
        elif root in _BLOCKING_ROOT and root != seg:
            what = _BLOCKING_ROOT[root]
        elif seg == "wait" or seg == "join":
            what = "thread-blocking wait"
        if what is None:
            return
        self.findings.append(Finding(
            "aio-blocking", self.model.relpath, node.lineno,
            f"{what} ({_dotted(func) or seg}) inside coroutine "
            f"{self.fn.name}: blocking the event loop stalls every "
            f"connection it serves"))


def _check_aio_blocking(model: ModuleModel,
                        findings: List[Finding]) -> None:
    for node in ast.walk(model.tree):
        if isinstance(node, ast.AsyncFunctionDef):
            visitor = _AioBlockingVisitor(model, node, findings)
            for stmt in node.body:
                visitor.visit(stmt)


def _check_edges(model: ModuleModel, config: AnalyzerConfig,
                 edges: List[Tuple[str, str, str, int]],
                 findings: List[Finding]) -> None:
    ranks = config.lock_ranks
    seen: Set[Tuple[str, str, int]] = set()
    for prev, new, site, lineno in edges:
        key = (prev, new, lineno)
        if key in seen:
            continue
        seen.add(key)
        rp, rn = ranks.get(prev), ranks.get(new)
        if rp is None or rn is None:
            missing = [n for n, r in ((prev, rp), (new, rn)) if r is None]
            findings.append(Finding(
                "lock-order", model.relpath, lineno,
                f"nested acquisition {prev} -> {new} involves lock(s) "
                f"not in lock_hierarchy.toml: {', '.join(missing)} "
                f"(declare a rank or restructure)"))
        elif rp >= rn:
            findings.append(Finding(
                "lock-order", model.relpath, lineno,
                f"nested acquisition {prev} (rank {rp}) -> {new} "
                f"(rank {rn}) inverts the declared hierarchy"))


def check_module(model: ModuleModel,
                 config: AnalyzerConfig) -> List[Finding]:
    findings: List[Finding] = []
    edges: List[Tuple[str, str, str, int]] = []
    hot = _in_scope(model.relpath, config.hot_path_fragments)
    if _in_scope(model.relpath, config.aio_path_fragments):
        _check_aio_blocking(model, findings)
    for cls, func in iter_functions(model):
        hook_list: List[Hooks] = [
            _GuardedByHooks(model, cls, func, findings),
            _LockOrderHooks(model, findings, edges),
        ]
        if hot:
            hook_list.append(_BlockUnderLockHooks(model, cls, findings))

        class _Fan(Hooks):
            def on_acquire(self, ref, held, node):
                for h in hook_list:
                    h.on_acquire(ref, held, node)

            def on_attr(self, node, held):
                for h in hook_list:
                    h.on_attr(node, held)

            def on_call(self, node, held):
                for h in hook_list:
                    h.on_call(node, held)

        HeldWalker(model, cls, func, _Fan()).run()
    _check_edges(model, config, edges, findings)
    return findings
