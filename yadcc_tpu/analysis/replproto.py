"""Replication / exactly-once protocol verifier (v4 rule pack).

The scheduler's contract is that a leased grant runs *exactly once*,
and PR 15 made that invariant distributed: a post-commit lease journal
(scheduler/replication.py), a two-level grant-id namespace
(cell x shard stride composition), and a standby takeover that must
open the adoption window before it starts serving.  This family checks
the code *structure* behind those invariants; the dynamic counterpart
(yadcc_tpu/testing/interleave.py) model-checks the same invariants
under bounded thread schedules.

Four rules, all scoped to the replication surface
(``AnalyzerConfig.replproto_path_fragments``) or to any file carrying
the directives:

* ``repl-journal-skip`` — a method declared
  ``# ytpu: replicated(op, ...)`` must pair every mutation path (a call
  through ``self._inner.*``) with a journal append of one of the
  declared ops, and the append must come AFTER the commit (the
  post-commit ordering is what makes a journal entry a promise the
  state change happened).  A declared op that is never appended on any
  path is also a finding — that is how the deliberate no-journal
  expiration path earns its written ``allow``.
* ``repl-journal-under-lock`` — a journal append (or a call to a
  same-class helper that appends) while ANY statically-held lock is
  held.  The journal lock is a rank-4 leaf; taking it under a
  dispatcher-rank lock is how replication gets to stall the grant
  path.
* ``grant-id-arith`` — bare arithmetic on grant-id-shaped names
  outside the blessed namespace helpers, plus a symbolic check that
  every ``grant_id_start=/grant_id_stride=`` construction site
  composes with the cell x shard stride math (start's constant term
  +1, every other term sharing a symbol with the stride product, at
  most one unit-coefficient shard-index term).
* ``takeover-order`` — a function declared
  ``# ytpu: protocol(a<b<c)`` must reach its protocol steps in the
  declared order on every path (loops are assumed to execute: an
  empty replay loop must not poison the order).

Honesty notes: the path walks are intraprocedural with one-hop helper
resolution (``self._journal_issue`` counts as appending "issue"), a
closure handed to the inner call as a callback credits its ops to the
whole function (the ``_submit``/``journaling_done`` idiom), and a
branch whose test mentions an inner-derived name (or a parameter) is
*credited* — its no-append arm is taken to be deliberate.  Raising
paths are exempt: the caller sees the failure.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .core import (AnalyzerConfig, Finding, FunctionInfo, HeldWalker,
                   Hooks, LockRef, ModuleModel, _dotted, iter_functions,
                   last_segment)
from .lockrules import _in_scope

# Function names whose bodies are the sanctioned home of grant-id
# arithmetic: the namespace constructors/decoders plus the adopted-id
# counter advance.
_BLESSED_FUNCS = {
    "grant_namespace_for_cell", "cell_of_grant", "shard_of_grant",
    "grant_id_start", "grant_id_stride", "_advance_grant_id_locked",
}

# Bare names that denote a grant id even without the substring.
_GRANT_NAMES = {"gid", "gids", "grant_ids", "floor_grant_id"}

_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod)

# Call-name -> protocol step (exact match; the step's own name always
# matches so fixtures can use bare step calls).
_STEP_ALIASES = {
    "keep_servant_alive": "replay",
    "adopt_grants": "adopt",
    "set_adoption_window": "window",
}

_STATE_CAP = 64  # path-state explosion bound, as in asyncproto


def _cap(states: set) -> set:
    if len(states) <= _STATE_CAP:
        return states
    return set(sorted(states, key=repr)[:_STATE_CAP])


# ---------------------------------------------------------------------------
# Shared event extraction.
# ---------------------------------------------------------------------------


def _journal_append_ops(node: ast.AST) -> Optional[Set[str]]:
    """The journal ops a call appends, or None when the call is not a
    journal append.  Matched on ``<...journal...>.append(...)``; the op
    comes from the ``"op"`` key of a dict-literal first argument, with
    ``"*"`` (satisfies any declared op) when it cannot be read."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr == "append"):
        return None
    owner = last_segment(f.value)
    if owner is None or "journal" not in owner:
        return None
    ops: Set[str] = set()
    if node.args and isinstance(node.args[0], ast.Dict):
        for k, v in zip(node.args[0].keys, node.args[0].values):
            if isinstance(k, ast.Constant) and k.value == "op" and \
                    isinstance(v, ast.Constant):
                ops.add(str(v.value))
    return ops or {"*"}


def _is_commit(call: ast.Call) -> bool:
    dotted = _dotted(call.func) or ""
    return dotted.startswith("self._inner.") or \
        dotted.startswith("self.inner.")


def _iter_events(stmts: Sequence[ast.AST],
                 appenders: Dict[str, Set[str]]
                 ) -> List[Tuple[str, FrozenSet[str], int]]:
    """("commit"|"append", ops, lineno) events in source order, nested
    defs/lambdas excluded (their bodies run later, not on this path)."""
    events: List[Tuple[str, FrozenSet[str], int]] = []

    def rec(n: ast.AST) -> None:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return
        if isinstance(n, ast.Call):
            if _is_commit(n):
                events.append(("commit", frozenset(), n.lineno))
            else:
                ops = _journal_append_ops(n)
                if ops is None and isinstance(n.func, ast.Attribute) and \
                        isinstance(n.func.value, ast.Name) and \
                        n.func.value.id == "self" and \
                        n.func.attr in appenders:
                    ops = appenders[n.func.attr]
                if ops is not None:
                    events.append(("append", frozenset(ops), n.lineno))
        for c in ast.iter_child_nodes(n):
            rec(c)

    for s in stmts:
        rec(s)
    return events


def _class_appenders(model: ModuleModel) -> Dict[str, Dict[str, Set[str]]]:
    """class name -> {method name -> ops it DIRECTLY journal-appends}
    (one-hop helper resolution for both path walks)."""
    out: Dict[str, Dict[str, Set[str]]] = {}
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        methods: Dict[str, Set[str]] = {}
        for sub in node.body:
            if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            ops: Set[str] = set()
            for n in ast.walk(sub):
                o = _journal_append_ops(n)
                if o:
                    ops |= o
            if ops:
                methods[sub.name] = ops
        out[node.name] = methods
    return out


# ---------------------------------------------------------------------------
# repl-journal-skip.
# ---------------------------------------------------------------------------


def _credited_names(func: ast.AST, params: Sequence[str]) -> Set[str]:
    """Names whose value derives from the inner dispatcher or a
    parameter: branches on them are deliberate journaling decisions."""
    credited = {p for p in params if p not in ("self", "cls")}

    def derived(value: ast.AST) -> bool:
        for n in ast.walk(value):
            if isinstance(n, ast.Name) and n.id in credited:
                return True
            if isinstance(n, ast.Attribute) and n.attr in ("_inner",
                                                           "inner"):
                return True
        return False

    changed = True
    while changed:
        changed = False
        for node in ast.walk(func):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign) and derived(node.value):
                targets = list(node.targets)
            elif isinstance(node, (ast.For, ast.AsyncFor)) and \
                    derived(node.iter):
                targets = [node.target]
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name) and n.id not in credited:
                        credited.add(n.id)
                        changed = True
    return credited


def _handoff_ops(func: ast.AST, appenders: Dict[str, Set[str]]
                 ) -> Set[str]:
    """Ops appended by a nested def that is handed to an inner-commit
    call as a callback: they count for the whole function (the journal
    fires when the inner dispatcher completes the hand-off)."""
    nested: Dict[str, Set[str]] = {}
    for n in ast.walk(func):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                n is not func:
            ops = {op for kind, evops, _ in _iter_events(n.body, appenders)
                   if kind == "append" for op in evops}
            if ops:
                nested[n.name] = ops
    out: Set[str] = set()
    if not nested:
        return out
    for n in ast.walk(func):
        if isinstance(n, ast.Call) and _is_commit(n):
            for sub in list(n.args) + [kw.value for kw in n.keywords]:
                if isinstance(sub, ast.Name) and sub.id in nested:
                    out |= nested[sub.id]
    return out


class _ReplWalk:
    """Path-sensitive walk of one replicated(...) method.

    State = (committed, appended-ops, credited).  Forks at If, loops as
    0-or-1, Try handlers entered from both the try entry and the end of
    the body, Raise paths exempt."""

    def __init__(self, info: FunctionInfo, appenders: Dict[str, Set[str]],
                 relpath: str, out: List[Finding]):
        self.declared = frozenset(info.replicated)
        self.appenders = appenders
        self.relpath = relpath
        self.out = out
        self.func = info.node
        self.credited = _credited_names(self.func, info.params)
        self.handoff = _handoff_ops(self.func, appenders)
        self.states: set = {(False, frozenset(), False)}
        self.seen_ops: Set[str] = set(self.handoff)
        self._fired: Set[Tuple[str, int]] = set()

    def run(self) -> None:
        self._walk_stmts(self.func.body)
        last = self.func.body[-1] if self.func.body else self.func
        self._terminal(getattr(last, "end_lineno", None) or last.lineno)
        for op in sorted(self.declared - self.seen_ops):
            if "*" in self.seen_ops:
                break
            self._fire(
                self.func.lineno,
                f"declared journal op '{op}' is never appended on any "
                f"path of this replicated method (a standby replaying "
                f"the journal will miss the mutation)")

    # -- events ------------------------------------------------------------

    def _fire(self, line: int, message: str) -> None:
        key = (message, line)
        if key in self._fired:
            return
        self._fired.add(key)
        self.out.append(Finding("repl-journal-skip", self.relpath, line,
                                message))

    def _apply_events(self, node: ast.AST) -> None:
        for kind, ops, line in _iter_events([node], self.appenders):
            if kind == "commit":
                self.states = _cap({(True, o, cr)
                                    for _, o, cr in self.states})
                continue
            self.seen_ops |= ops
            new = set()
            for committed, have, cr in self.states:
                if not committed:
                    self._fire(
                        line,
                        "journal append before the inner commit on this "
                        "path: the entry promises a state change that "
                        "has not happened yet (post-commit ordering is "
                        "the exactly-once contract)")
                new.add((committed, have | ops, cr))
            self.states = _cap(new)

    def _terminal(self, line: int) -> None:
        for committed, have, credited in self.states:
            if not committed or credited:
                continue
            if "*" in have or (self.declared & (have | self.handoff)):
                continue
            self._fire(
                line,
                "mutation path commits via self._inner but reaches "
                "return without a journal append of any declared op "
                f"({', '.join(sorted(self.declared))}): a takeover "
                "replays a mirror that never saw this change")

    # -- control flow ------------------------------------------------------

    def _credited_test(self, test: ast.AST) -> bool:
        for n in ast.walk(test):
            if isinstance(n, ast.Name) and n.id in self.credited:
                return True
            if isinstance(n, ast.Attribute) and n.attr in ("_inner",
                                                           "inner"):
                return True
        return False

    def _walk_stmts(self, stmts: Sequence[ast.AST]) -> None:
        for s in stmts:
            self._walk_stmt(s)

    def _walk_stmt(self, s: ast.AST) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return
        if isinstance(s, ast.If):
            self._apply_events(s.test)
            credited = self._credited_test(s.test)
            entry = set(self.states)
            if credited:
                self.states = {(c, o, True) for c, o, _ in self.states}
            self._walk_stmts(s.body)
            body_out = self.states
            self.states = ({(c, o, True) for c, o, _ in entry}
                           if credited else set(entry))
            self._walk_stmts(s.orelse)
            self.states = _cap(body_out | self.states)
            return
        if isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
            self._apply_events(s.iter if isinstance(s, (ast.For,
                                                        ast.AsyncFor))
                               else s.test)
            skip = set(self.states)
            self._walk_stmts(s.body)
            self.states = _cap(self.states | skip)
            if s.orelse:
                self._walk_stmts(s.orelse)
            return
        if isinstance(s, ast.Try):
            entry = set(self.states)
            self._walk_stmts(s.body)
            after_body = set(self.states)
            handler_out: set = set()
            for h in s.handlers:
                self.states = _cap(entry | after_body)
                self._walk_stmts(h.body)
                handler_out |= self.states
            self.states = _cap(after_body | handler_out)
            if s.orelse:
                self._walk_stmts(s.orelse)
            if s.finalbody:
                self._walk_stmts(s.finalbody)
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self._apply_events(item.context_expr)
            self._walk_stmts(s.body)
            return
        if isinstance(s, ast.Return):
            if s.value is not None:
                self._apply_events(s.value)
            self._terminal(s.lineno)
            self.states = set()
            return
        if isinstance(s, ast.Raise):
            self.states = set()  # propagating failure: caller sees it
            return
        self._apply_events(s)


# ---------------------------------------------------------------------------
# repl-journal-under-lock.
# ---------------------------------------------------------------------------


class _JournalLockHooks(Hooks):
    def __init__(self, relpath: str, appenders: Dict[str, Set[str]],
                 config: AnalyzerConfig, out: List[Finding]):
        self.relpath = relpath
        self.appenders = appenders
        self.config = config
        self.out = out
        self._seen: Set[int] = set()

    def on_call(self, node: ast.Call, held: List[LockRef]) -> None:
        if not held or node.lineno in self._seen:
            return
        is_append = _journal_append_ops(node) is not None
        if not is_append and isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "self" and \
                node.func.attr in self.appenders:
            is_append = True
        if not is_append:
            return
        self._seen.add(node.lineno)
        descr = []
        for ref in held:
            rank = self.config.lock_ranks.get(ref.key)
            descr.append(f"{ref.key} (rank {rank})" if rank is not None
                         else f"{ref.key} (undeclared rank)")
        self.out.append(Finding(
            "repl-journal-under-lock", self.relpath, node.lineno,
            f"journal append while holding {', '.join(descr)}: the "
            f"journal lock is a rank-4 leaf taken at the call "
            f"boundary only — appending under a dispatcher lock lets "
            f"a wedged standby stall the grant path"))


# ---------------------------------------------------------------------------
# grant-id-arith.
# ---------------------------------------------------------------------------


def _grantish(name: Optional[str]) -> bool:
    return name is not None and ("grant_id" in name or
                                 name in _GRANT_NAMES)


def _subtree_grantish(node: ast.AST) -> Optional[str]:
    """First grant-id-shaped name in the subtree, skipping ``len(...)``
    arguments (sizing a buffer by a grant list is not id math)."""

    def rec(n: ast.AST) -> Optional[str]:
        if isinstance(n, ast.Call) and last_segment(n.func) == "len":
            return None
        seg = None
        if isinstance(n, ast.Name):
            seg = n.id
        elif isinstance(n, ast.Attribute):
            seg = n.attr
        if seg is not None and _grantish(seg):
            return seg
        for c in ast.iter_child_nodes(n):
            hit = rec(c)
            if hit is not None:
                return hit
        return None

    return rec(node)


_Poly = Dict[Tuple[str, ...], int]


def _poly(node: ast.AST) -> Optional[_Poly]:
    """node -> {sorted symbol tuple -> int coeff}, or None when the
    expression is outside the +,-,* / int() fragment (site skipped)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return {(): node.value}
    if isinstance(node, ast.Name):
        return {(node.id,): 1}
    if isinstance(node, ast.Attribute):
        d = _dotted(node) or node.attr
        return {(d,): 1}
    if isinstance(node, ast.Call) and last_segment(node.func) == "int" \
            and len(node.args) == 1 and not node.keywords:
        return _poly(node.args[0])
    if isinstance(node, ast.BinOp) and \
            isinstance(node.op, (ast.Add, ast.Sub, ast.Mult)):
        left, right = _poly(node.left), _poly(node.right)
        if left is None or right is None:
            return None
        out: _Poly = {}
        if isinstance(node.op, ast.Mult):
            for ka, va in left.items():
                for kb, vb in right.items():
                    key = tuple(sorted(ka + kb))
                    out[key] = out.get(key, 0) + va * vb
        else:
            sign = -1 if isinstance(node.op, ast.Sub) else 1
            out = dict(left)
            for k, v in right.items():
                out[k] = out.get(k, 0) + sign * v
        return {k: v for k, v in out.items() if v != 0}
    return None


def _check_namespace_site(call: ast.Call, relpath: str,
                          out: List[Finding]) -> None:
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    if "grant_id_start" not in kw or "grant_id_stride" not in kw:
        return
    stride = _poly(kw["grant_id_stride"])
    start = _poly(kw["grant_id_start"])
    if stride is None or start is None:
        return  # outside the symbolic fragment: the dynamic side owns it

    def fire(msg: str) -> None:
        out.append(Finding(
            "grant-id-arith", relpath, call.lineno,
            f"(grant_id_start, grant_id_stride) construction does not "
            f"compose with the cell x shard namespace: {msg}"))

    if len(stride) != 1:
        fire("stride must be a single product term (cells x shards), "
             f"got {len(stride)} terms")
        return
    (skey, scoeff), = stride.items()
    if skey == ():
        if scoeff < 1:
            fire(f"constant stride {scoeff} < 1")
        elif set(start) - {()} or not 1 <= start.get((), 0) <= scoeff:
            fire("with a constant stride the start must be a constant "
                 "in [1, stride]")
        return
    if scoeff != 1:
        fire(f"stride product carries coefficient {scoeff} (must be 1: "
             f"one id per (cell, shard) residue)")
        return
    rest = dict(start)
    const = rest.pop((), 0)
    if const != 1:
        fire(f"start's constant term is {const}, not +1 (ids are "
             f"1-based; residue 0 would alias the unset sentinel)")
    disjoint = 0
    for tkey, tcoeff in rest.items():
        if set(tkey) & set(skey):
            continue
        disjoint += 1 if tcoeff == 1 else 2
    if disjoint > 1:
        fire("start has more than one term disjoint from the stride "
             "product: only the unit-coefficient shard index may stand "
             "alone")


class _GrantArithVisitor:
    def __init__(self, relpath: str, out: List[Finding]):
        self.relpath = relpath
        self.out = out

    def visit(self, node: ast.AST, exempt: bool = False) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                node.name in _BLESSED_FUNCS:
            return  # the sanctioned home of the arithmetic
        if isinstance(node, (ast.Compare, ast.JoinedStr)):
            # Comparisons (residue/range checks) and f-strings
            # (diagnostics) read ids; they cannot mint a wrong one.
            exempt = True
        fired = False
        if not exempt and isinstance(node, ast.BinOp) and \
                isinstance(node.op, _ARITH_OPS):
            seg = _subtree_grantish(node)
            if seg is not None:
                self._fire(node.lineno, seg)
                fired = True
        if not exempt and isinstance(node, ast.AugAssign) and \
                isinstance(node.op, _ARITH_OPS):
            seg = (_subtree_grantish(node.target)
                   or _subtree_grantish(node.value))
            if seg is not None:
                self._fire(node.lineno, seg)
                fired = True
        if isinstance(node, ast.Call):
            _check_namespace_site(node, self.relpath, self.out)
        for child in ast.iter_child_nodes(node):
            self.visit(child, exempt or fired)

    def _fire(self, line: int, seg: str) -> None:
        self.out.append(Finding(
            "grant-id-arith", self.relpath, line,
            f"bare arithmetic on grant id '{seg}' outside the blessed "
            f"namespace helpers "
            f"({', '.join(sorted(_BLESSED_FUNCS))}): id math that "
            f"ignores the cell x shard stride can collide namespaces"))


# ---------------------------------------------------------------------------
# takeover-order.
# ---------------------------------------------------------------------------


class _ProtoWalk:
    """Ordered-protocol walk: every declared step reached on a path
    must find all earlier declared steps already done.  Loops are
    assumed to execute (an empty replay loop must not fail takeover);
    Try handlers fork from the try entry; Raise paths are exempt."""

    def __init__(self, info: FunctionInfo, relpath: str,
                 out: List[Finding]):
        self.steps = list(info.protocol)
        self.relpath = relpath
        self.out = out
        self.func = info.node
        self.states: set = {frozenset()}
        self._fired: Set[Tuple[int, str, str]] = set()

    def run(self) -> None:
        self._walk_stmts(self.func.body)

    def _step_for_call(self, call: ast.Call) -> Optional[str]:
        seg = last_segment(call.func)
        if seg is None:
            return None
        if seg in self.steps:
            return seg
        alias = _STEP_ALIASES.get(seg)
        return alias if alias in self.steps else None

    def _apply_events(self, node: ast.AST) -> None:
        events: List[Tuple[str, int]] = []

        def rec(n: ast.AST) -> None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return
            if isinstance(n, ast.Call):
                step = self._step_for_call(n)
                if step is not None:
                    events.append((step, n.lineno))
            for c in ast.iter_child_nodes(n):
                rec(c)

        rec(node)
        for step, line in events:
            idx = self.steps.index(step)
            new = set()
            for st in self.states:
                for earlier in self.steps[:idx]:
                    if earlier not in st:
                        key = (line, step, earlier)
                        if key not in self._fired:
                            self._fired.add(key)
                            self.out.append(Finding(
                                "takeover-order", self.relpath, line,
                                f"protocol step '{step}' reached before "
                                f"'{earlier}' (declared order: "
                                f"{' < '.join(self.steps)})"))
                new.add(st | {step})
            self.states = _cap(new)

    def _walk_stmts(self, stmts: Sequence[ast.AST]) -> None:
        for s in stmts:
            self._walk_stmt(s)

    def _walk_stmt(self, s: ast.AST) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return
        if isinstance(s, ast.If):
            self._apply_events(s.test)
            entry = set(self.states)
            self._walk_stmts(s.body)
            body_out = self.states
            self.states = set(entry)
            self._walk_stmts(s.orelse)
            self.states = _cap(body_out | self.states)
            return
        if isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
            self._apply_events(s.iter if isinstance(s, (ast.For,
                                                        ast.AsyncFor))
                               else s.test)
            self._walk_stmts(s.body)  # executes-once: steps DO happen
            if s.orelse:
                self._walk_stmts(s.orelse)
            return
        if isinstance(s, ast.Try):
            entry = set(self.states)
            self._walk_stmts(s.body)
            after_body = set(self.states)
            handler_out: set = set()
            for h in s.handlers:
                self.states = _cap(entry | after_body)
                self._walk_stmts(h.body)
                handler_out |= self.states
            self.states = _cap(after_body | handler_out)
            if s.orelse:
                self._walk_stmts(s.orelse)
            if s.finalbody:
                self._walk_stmts(s.finalbody)
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self._apply_events(item.context_expr)
            self._walk_stmts(s.body)
            return
        if isinstance(s, ast.Return):
            if s.value is not None:
                self._apply_events(s.value)
            self.states = set()
            return
        if isinstance(s, ast.Raise):
            self.states = set()
            return
        self._apply_events(s)


# ---------------------------------------------------------------------------
# Entry point.
# ---------------------------------------------------------------------------


def check_module(model: ModuleModel, functions: List[FunctionInfo],
                 config: AnalyzerConfig) -> List[Finding]:
    d = model.directives
    if not (_in_scope(model.relpath, config.replproto_path_fragments)
            or d.replicated or d.protocol):
        return []
    out: List[Finding] = []
    appenders_by_class = _class_appenders(model)

    for info in functions:
        if info.node is None:
            continue
        appenders = appenders_by_class.get(info.cls or "", {})
        if info.replicated:
            _ReplWalk(info, appenders, model.relpath, out).run()
        if info.protocol:
            _ProtoWalk(info, model.relpath, out).run()

    for cls, func in iter_functions(model):
        appenders = appenders_by_class.get(cls.name if cls else "", {})
        hooks = _JournalLockHooks(model.relpath, appenders, config, out)
        HeldWalker(model, cls, func, hooks).run()

    _GrantArithVisitor(model.relpath, out).visit(model.tree)
    return out
