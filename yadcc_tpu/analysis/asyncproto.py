"""Rule families 8-11: async-protocol verification for the parked
serving plane (reply-once / await-under-lock / loop-affinity /
async-lifecycle).

PR 10 moved the serving path onto one asyncio event loop: long-polls
park as continuations (`ServiceSpec.add_parked`), replies travel
through reply-once responder objects, deadlines are `call_later`
timers.  yadcc gets the equivalent guarantees from flare's fiber
runtime; here the discipline is hand-written protocol, so this pack
machine-checks it:

* **reply-once** (`reply-drop` / `reply-double` / `reply-handoff`) —
  parameters declared ``# ytpu: responder(param)`` on a def are checked
  on every execution path: each terminating path must either invoke the
  responder's reply surface exactly once, hand the responder off to a
  callee (whose receiving parameter must itself be declared), or raise
  (the parked dispatcher's error edge completes the stream).  A path
  with zero replies drops the parked client forever; a reachable second
  direct reply double-fires into a settled stream.  The walk is a
  path-sensitive abstract interpretation over (direct, transfer) reply
  counts — branches fork the state set, exception edges count ``raise``
  as legal completion, ``if <resp>.replied:`` guards credit the guarded
  branch, and nested defs capturing the responder are checked as
  responder contexts of their own.  Hand-offs resolve interprocedurally
  by callee name with taint.py's discipline (≤3 candidates, stoplist,
  summary-driven so cache hits stay correct).
* **await-under-lock** — an ``await`` while a ``threading`` lock is
  statically held (lexically inside ``with self._lock`` or in a
  ``*_locked`` convention method) stalls every parked client behind one
  critical section.  ``asyncio.Lock`` is exempt (core._factory_kind
  ignores asyncio-rooted factories).
* **loop-affinity** (`loop-affinity`) — defs declared
  ``# ytpu: loop-only`` may only be called from loop context: async
  defs, other loop-only defs, or thunks that demonstrably travel
  through the ``call_soon``/``call_soon_threadsafe`` seam.  Direct use
  of loop-affine primitives (``loop.call_later``, ``loop.create_task``,
  ``Future.set_result``...) outside loop context is likewise flagged.
* **async-lifecycle** (`async-timer-leak` / `async-task-orphan`) —
  ``call_later`` handles must be retained (a dropped handle can never
  be cancelled, so the timer outlives the continuation it guards) and
  local handles must be cancelled or handed off on completion paths;
  ``asyncio.create_task``/``loop.create_task`` results must be
  retained and awaited/cancelled/stored (orphaned fire-and-forget
  tasks are collected mid-flight and eat exceptions).

Scope: ``asyncproto_path_fragments`` (rpc/, scheduler/, daemon/).
Like every other family the pass errs toward false negatives:
unresolvable hand-offs (escaping into containers, >3 candidates,
stoplisted names) end the check for that path rather than guessing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    AnalyzerConfig,
    Finding,
    FunctionInfo,
    ModuleModel,
    last_segment,
    root_segment,
)

# Callee names too generic to resolve by name (mirrors taint.py).
_RESOLUTION_STOPLIST = {
    "get", "put", "add", "pop", "update", "append", "remove", "close",
    "start", "stop", "run", "call", "write", "join", "split", "items",
    "keys", "values", "copy", "encode", "decode", "send", "recv",
    "result", "acquire", "release", "format", "strip",
}
_MAX_CANDIDATES = 3

# Reply surfaces: calling <responder>.<one of these>(...) IS the reply.
_REPLY_METHODS = {
    "_reply", "reply", "send_result", "send_error", "set_result",
    "set_exception", "fire", "complete",
}
# Executor/loop seams whose fn-reference argument is *invoked later*:
# passing the responder to fn's closure (or as a trailing arg) is a
# transfer, and the fn-reference itself gets a synthesized call edge.
_SEAM_SEGS = {"submit", "call_soon", "call_soon_threadsafe",
              "call_later", "add_done_callback"}

# Loop-affine primitives: only legal from loop context.
_LOOP_AFFINE_SEGS = {"call_later", "create_task", "ensure_future",
                     "add_reader", "add_writer"}
# Thread-safe seams that make an off-loop call legal.
_THREADSAFE_SEGS = {"call_soon_threadsafe", "run_coroutine_threadsafe",
                    "run_sync"}

# Timer-producing calls (handle must be retained): last segment.
_TIMER_SEGS = {"call_later", "call_at"}
# Task-producing calls (result must be retained): last segment.
_TASK_SEGS = {"create_task", "ensure_future"}
# Methods that legally settle a retained handle.
_SETTLE_SEGS = {"cancel", "cancelled"}


def _in_scope(relpath: str, fragments: Tuple[str, ...]) -> bool:
    parts = relpath.replace("\\", "/").split("/")
    return any(frag in parts for frag in fragments)


def _is_constructor_name(name: str) -> bool:
    base = name.lstrip("_")
    return bool(base) and base[0].isupper() and not base.isupper()


# ---------------------------------------------------------------------------
# reply-once: per-function path walk.
# ---------------------------------------------------------------------------

# A path state is (direct_replies, transfers), both capped so the state
# set stays tiny.  `None` in a state set position never occurs; states
# are frozensets of (d, t) pairs.
_CAP = 2


def _bump(states: Set[Tuple[int, int]], dd: int = 0,
          dt: int = 0) -> Set[Tuple[int, int]]:
    return {(min(d + dd, _CAP), min(t + dt, _CAP)) for d, t in states}


class _ReplyWalk:
    """All-paths walk of one responder context (a def plus the nested
    defs that do NOT capture the responder).  Produces:

    * terminal path states (fell off the end / explicit return),
    * raise path states (legal completion via the dispatcher error edge),
    * double-fire sites (line numbers where a path's direct count hit 2),
    * hand-off records for the global resolution pass,
    * closures: nested defs capturing the responder (checked separately
      as their own responder contexts by the caller).
    """

    def __init__(self, resp: str, func: ast.AST):
        self.resp = resp
        self.aliases: Set[str] = {resp}
        self.func = func
        self.doubles: List[int] = []
        self.handoffs: List[dict] = []
        self.closures: List[ast.AST] = []
        self.raise_states: Set[Tuple[int, int]] = set()
        self.escaped = False   # responder stored/escaped unresolvably

    # -- expression helpers ------------------------------------------------

    def _is_resp(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Name) and node.id in self.aliases

    def _mentions_resp(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if self._is_resp(sub):
                return True
        return False

    def _reply_call(self, node: ast.Call) -> bool:
        """`<resp>(...)` (callable continuations like `done`/`on_done`)
        or `<resp>.reply-ish(...)` (responder objects) — the direct
        reply surface."""
        f = node.func
        if self._is_resp(f):
            return True
        return (isinstance(f, ast.Attribute) and self._is_resp(f.value)
                and f.attr in _REPLY_METHODS)

    def _capturing_def(self, node: ast.AST) -> bool:
        """Does this nested def's body reference the responder without
        redefining it as a parameter?"""
        args = getattr(node, "args", None)
        if args is not None:
            params = {p.arg for p in
                      (args.posonlyargs + args.args + args.kwonlyargs)}
            if self.aliases & params:
                return False
        body = getattr(node, "body", [])
        stmts = body if isinstance(body, list) else [body]
        for stmt in stmts:
            for sub in ast.walk(stmt):
                if self._is_resp(sub):
                    return True
        return False

    def _replied_guard(self, test: ast.AST) -> Optional[bool]:
        """`if <resp>.replied:` -> True (body branch is post-reply);
        `if not <resp>.replied:` -> False (else branch is post-reply);
        anything else -> None."""
        neg = False
        while isinstance(test, ast.UnaryOp) and \
                isinstance(test.op, ast.Not):
            neg = not neg
            test = test.operand
        # Accept the guard attribute anywhere in an `or` chain:
        # `if resp.replied or result is None:` guards its body too
        # (every reply-bearing continuation uses this shape).
        candidates = [test]
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
            candidates = list(test.values)
        for c in candidates:
            if isinstance(c, ast.Attribute) and self._is_resp(c.value) \
                    and c.attr in ("replied", "fired", "done"):
                return not neg
        return None

    # -- call classification -----------------------------------------------

    def _classify_call(self, node: ast.Call) -> Optional[dict]:
        """If the responder flows into this call, return a hand-off
        record (callee/pos/kw/method/line) or mark escape.  Reply calls
        are handled by the caller before this."""
        fname = last_segment(node.func)
        # Executor seam: submit(fn, resp, ...) / call_soon(fn, resp...)
        # -> synthesized edge to `fn` with the responder's position
        # shifted left by one (fn receives it as its own argument).
        if fname in _SEAM_SEGS and node.args:
            fn_ref = node.args[0]
            fn_name = last_segment(fn_ref)
            # call_later(delay, fn, *args): fn is arg[1].
            shift = 1
            if fname in ("call_later", "call_at") and len(node.args) >= 2:
                fn_ref = node.args[1]
                fn_name = last_segment(fn_ref)
                shift = 2
            for i, a in enumerate(node.args[shift:]):
                if self._is_resp(a):
                    if fn_name is None:
                        self.escaped = True
                        return None
                    return {"callee": fn_name, "pos": i, "kw": None,
                            "method": isinstance(fn_ref, ast.Attribute),
                            "line": node.lineno, "seam": fname}
            # Responder captured by a closure passed through the seam is
            # handled by the closure check; a bare fn that IS an alias
            # (seam invokes the responder itself) cannot reply.
            if self._mentions_resp(node):
                for kw in node.keywords:
                    if kw.value is not None and \
                            self._mentions_resp(kw.value):
                        self.escaped = True
                        return None
            return None
        # Plain call with the responder as an argument.
        for i, a in enumerate(node.args):
            if self._is_resp(a):
                if fname is None or _is_constructor_name(fname):
                    # Constructors retain the responder as state: a
                    # transfer we cannot follow — treated as a legal
                    # hand-off (the retaining object owns the reply).
                    return {"callee": None, "pos": i, "kw": None,
                            "method": False, "line": node.lineno,
                            "seam": None}
                return {"callee": fname, "pos": i, "kw": None,
                        "method": isinstance(node.func, ast.Attribute),
                        "line": node.lineno, "seam": None}
        for kw in node.keywords:
            if kw.arg is not None and self._is_resp(kw.value):
                if fname is None or _is_constructor_name(fname):
                    return {"callee": None, "pos": None, "kw": kw.arg,
                            "method": False, "line": node.lineno,
                            "seam": None}
                return {"callee": fname, "pos": None, "kw": kw.arg,
                        "method": isinstance(node.func, ast.Attribute),
                        "line": node.lineno, "seam": None}
            if kw.arg is None and kw.value is not None and \
                    self._mentions_resp(kw.value):
                self.escaped = True
        return None

    # -- statement walk (forks state sets) ---------------------------------

    def _scan_expr(self, node: ast.AST,
                   states: Set[Tuple[int, int]]) -> Set[Tuple[int, int]]:
        """Evaluate an expression for reply/hand-off effects, in
        syntactic order.  Returns the updated state set."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            if self._capturing_def(node):
                self.closures.append(node)
            return states
        if isinstance(node, ast.Call):
            # Arguments evaluate first.
            for a in node.args:
                states = self._scan_expr(a, states)
            for kw in node.keywords:
                states = self._scan_expr(kw.value, states)
            states = self._scan_expr(node.func, states)
            if self._reply_call(node):
                for d, t in states:
                    if d + 1 >= 2:
                        self.doubles.append(node.lineno)
                        break
                return _bump(states, dd=1)
            rec = self._classify_call(node)
            if rec is not None:
                self.handoffs.append(rec)
                return _bump(states, dt=1)
            return states
        if isinstance(node, ast.Await):
            return self._scan_expr(node.value, states)
        # Bare `resp` in a return/assign RHS outside a call: escape.
        for child in ast.iter_child_nodes(node):
            states = self._scan_expr(child, states)
        return states

    def walk_body(self, stmts: Sequence[ast.AST],
                  states: Set[Tuple[int, int]]) -> Set[Tuple[int, int]]:
        """Returns the fall-through state set; terminated paths (return/
        raise/continue/break) leave via self.terminal/raise_states."""
        for stmt in stmts:
            states = self._walk_stmt(stmt, states)
            if not states:
                break
        return states

    def _walk_stmt(self, node: ast.AST,
                   states: Set[Tuple[int, int]]) -> Set[Tuple[int, int]]:
        if not states:
            return states
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if self._capturing_def(node):
                self.closures.append(node)
            return states
        if isinstance(node, ast.ClassDef):
            return states
        if isinstance(node, ast.Return):
            if node.value is not None:
                if self._is_resp(node.value):
                    # Returning the responder hands it to the caller.
                    states = _bump(states, dt=1)
                else:
                    states = self._scan_expr(node.value, states)
            self.terminal |= states
            return set()
        if isinstance(node, ast.Raise):
            for child in ast.iter_child_nodes(node):
                self._scan_expr(child, set(states))
            self.raise_states |= states
            return set()
        if isinstance(node, ast.If):
            states = self._scan_expr(node.test, states)
            guard = self._replied_guard(node.test)
            body_in = set(states)
            else_in = set(states)
            if guard is True:
                body_in = _bump(body_in, dt=1)
            elif guard is False:
                else_in = _bump(else_in, dt=1)
            out = self.walk_body(node.body, body_in)
            out |= self.walk_body(node.orelse, else_in)
            return out
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            if isinstance(node, ast.While):
                states = self._scan_expr(node.test, states)
            else:
                states = self._scan_expr(node.iter, states)
            # Loop body: 0-or-1 executions approximate reply counting
            # (a reply in a loop that runs twice is a double; we accept
            # the false negative like the other families).
            once = self.walk_body(node.body, set(states))
            merged = states | once
            merged |= self.walk_body(node.orelse, set(merged))
            return merged
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                states = self._scan_expr(item.context_expr, states)
            return self.walk_body(node.body, states)
        if isinstance(node, ast.Try):
            body_out = self.walk_body(node.body, set(states))
            # Exception edge: any prefix of the body may have run.  The
            # pre-body state enters every handler; a reply inside the
            # try is assumed settled before the raise for count
            # purposes (the runtime once-guard absorbs the overlap).
            out: Set[Tuple[int, int]] = set()
            for h in node.handlers:
                out |= self.walk_body(h.body, set(states))
            out |= self.walk_body(node.orelse, set(body_out))
            if node.finalbody:
                out = self.walk_body(node.finalbody,
                                     out | body_out if not node.orelse
                                     else out)
            elif not node.orelse:
                out |= body_out
            return out
        if isinstance(node, ast.Assign):
            states = self._scan_expr(node.value, states)
            # `alias = resp` propagates the responder name.
            if self._is_resp(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.aliases.add(tgt.id)
                    else:
                        # Stored into an attribute/subscript: the
                        # container owns it now — transfer.
                        states = _bump(states, dt=1)
            elif any(self._mentions_resp(t) for t in node.targets):
                pass
            return states
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if node.value is not None:
                states = self._scan_expr(node.value, states)
            return states
        if isinstance(node, ast.Expr):
            return self._scan_expr(node.value, states)
        if isinstance(node, (ast.Break, ast.Continue)):
            self.terminal |= states
            return set()
        if isinstance(node, ast.Assert):
            for child in ast.iter_child_nodes(node):
                states = self._scan_expr(child, states)
            return states
        # Fallback: scan children generically.
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                states = self._walk_stmt(child, states)
            else:
                states = self._scan_expr(child, states)
        return states

    def run(self) -> dict:
        self.terminal: Set[Tuple[int, int]] = set()
        body = getattr(self.func, "body", [])
        stmts = body if isinstance(body, list) else [ast.Expr(body)]
        fall = self.walk_body(stmts, {(0, 0)})
        self.terminal |= fall
        return {
            "terminal": sorted(self.terminal),
            "raises": sorted(self.raise_states),
            "doubles": sorted(set(self.doubles)),
            "handoffs": self.handoffs,
            "escaped": self.escaped,
            "closures": self.closures,
        }


def _responder_params(info: FunctionInfo) -> List[str]:
    return [p for p in info.responders if p in info.params]


def summarize_functions(model: ModuleModel,
                        functions: List[FunctionInfo]) -> None:
    """Attach the JSON-serializable reply-once summary (`asyncp`) to
    each responder-annotated def so the global hand-off resolution pass
    works identically on cached and fresh files."""
    for info in functions:
        rps = _responder_params(info)
        bad = [p for p in info.responders if p not in info.params]
        if not rps and not bad:
            info.asyncp = None
            continue
        summary: dict = {"bad_decls": bad, "by_param": {}}
        if info.node is not None:
            for resp in rps:
                walk = _ReplyWalk(resp, info.node)
                res = walk.run()
                # Closures capturing the responder: each is a responder
                # context of its own; the outer body treats the closure
                # *name* as an alias so passing it through a seam is a
                # transfer.  We walk them here and fold their verdicts
                # into per-closure entries.
                closures = []
                for cnode in res.pop("closures"):
                    cwalk = _ReplyWalk(resp, cnode)
                    cres = cwalk.run()
                    cres.pop("closures")
                    closures.append({
                        "name": getattr(cnode, "name", "<lambda>"),
                        "line": cnode.lineno, **cres})
                res["closures"] = closures
                summary["by_param"][resp] = res
        info.asyncp = summary


# ---------------------------------------------------------------------------
# reply-once: verdicts (module-local part) + global hand-off resolution.
# ---------------------------------------------------------------------------


def _judge_context(name: str, relpath: str, line: int, res: dict,
                   findings: List[Finding], *,
                   outer_has_closures: bool = False) -> None:
    """Verdicts that need no interprocedural info: double-fire and
    dropped-client paths.  A context that hands the responder off or
    escapes it is exempt from the drop check (the recipient owns it);
    hand-off *target* validation happens globally."""
    for ln in res["doubles"]:
        findings.append(Finding(
            "reply-double", relpath, ln,
            f"{name}: a second direct reply is reachable on one "
            f"execution path (double-fire into a settled stream)"))
    if res["escaped"]:
        return
    drop = [s for s in res["terminal"] if s[0] + s[1] == 0]
    if drop and not outer_has_closures:
        findings.append(Finding(
            "reply-drop", relpath, line,
            f"{name}: a path neither replies, hands the responder "
            f"off, nor raises — the parked client is dropped"))


def check_module(model: ModuleModel, functions: List[FunctionInfo],
                 config: AnalyzerConfig,
                 loop_only_names: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    if not _in_scope(model.relpath, config.asyncproto_path_fragments):
        return findings
    findings.extend(_check_reply_local(model, functions))
    findings.extend(_check_await_under_lock(model, config))
    findings.extend(_check_loop_affinity(model, functions,
                                         loop_only_names))
    findings.extend(_check_async_lifecycle(model, functions))
    return findings


def _check_reply_local(model: ModuleModel,
                       functions: List[FunctionInfo]) -> List[Finding]:
    findings: List[Finding] = []
    for info in functions:
        if not info.asyncp:
            continue
        for bad in info.asyncp.get("bad_decls", ()):
            findings.append(Finding(
                "reply-drop", info.relpath, info.lineno,
                f"responder({bad}) names no parameter of {info.name}"))
        for resp, res in info.asyncp.get("by_param", {}).items():
            ctx = f"{info.name}({resp})"
            # A def whose responder only ever escapes into closures:
            # the closures carry the reply obligation.
            closures = res.get("closures", ())
            _judge_context(ctx, info.relpath, info.lineno, res, findings,
                           outer_has_closures=bool(closures))
            for c in closures:
                _judge_context(f"{info.name}.{c['name']}({resp})",
                               info.relpath, c["line"], c, findings)
    return findings


def check_global(functions: Sequence[FunctionInfo],
                 config: AnalyzerConfig) -> List[Finding]:
    """reply-handoff: every resolvable hand-off target's receiving
    parameter must itself be declared ``# ytpu: responder(param)`` —
    the chain of custody is closed by declaration, so a forgotten
    annotation (an unchecked link) is itself the finding."""
    findings: List[Finding] = []
    by_name: Dict[str, List[FunctionInfo]] = {}
    for info in functions:
        by_name.setdefault(info.name, []).append(info)

    def resolve(rec: dict) -> Optional[List[FunctionInfo]]:
        callee = rec.get("callee")
        if callee is None or callee in _RESOLUTION_STOPLIST:
            return None
        cands = by_name.get(callee, [])
        if not cands or len(cands) > _MAX_CANDIDATES:
            return None
        return cands

    for info in functions:
        if not info.asyncp or not _in_scope(
                info.relpath, config.asyncproto_path_fragments):
            continue
        contexts = []
        for resp, res in info.asyncp.get("by_param", {}).items():
            contexts.append((resp, res))
            contexts.extend((resp, c) for c in res.get("closures", ()))
        for resp, res in contexts:
            for rec in res.get("handoffs", ()):
                cands = resolve(rec)
                if cands is None:
                    continue
                for cand in cands:
                    plist = list(cand.params)
                    if rec.get("method") and plist and \
                            plist[0] == "self":
                        plist = plist[1:]
                    target: Optional[str] = None
                    if rec.get("kw") is not None:
                        if rec["kw"] in plist:
                            target = rec["kw"]
                    elif rec.get("pos") is not None and \
                            rec["pos"] < len(plist):
                        target = plist[rec["pos"]]
                    if target is None:
                        continue
                    if target not in cand.responders:
                        findings.append(Finding(
                            "reply-handoff", info.relpath, rec["line"],
                            f"{info.name} hands responder '{resp}' to "
                            f"{cand.name}({target}=...) but "
                            f"{cand.relpath}:{cand.lineno} does not "
                            f"declare '# ytpu: responder({target})'"))
    return findings


# ---------------------------------------------------------------------------
# await-under-lock.
# ---------------------------------------------------------------------------


def _check_await_under_lock(model: ModuleModel,
                            config: AnalyzerConfig) -> List[Finding]:
    from .core import HeldWalker, Hooks, iter_functions

    findings: List[Finding] = []

    class _AwaitHooks(Hooks):
        def on_await(self, node: ast.Await, held) -> None:
            if held:
                locks = ", ".join(sorted({h.key for h in held}))
                findings.append(Finding(
                    "await-under-lock", model.relpath, node.lineno,
                    f"await while holding threading lock(s) {locks}: "
                    f"every parked continuation on this loop stalls "
                    f"behind the critical section"))

    for cls, func in iter_functions(model):
        HeldWalker(model, cls, func, _AwaitHooks()).run()
    return findings


# ---------------------------------------------------------------------------
# loop-affinity.
# ---------------------------------------------------------------------------


def _loop_context_def(node: ast.AST, info: FunctionInfo) -> bool:
    """Is this def itself loop context?  Async defs and declared
    loop-only defs are; everything else is pool/thread context."""
    return isinstance(node, ast.AsyncFunctionDef) or info.loop_only


class _AffinityVisitor(ast.NodeVisitor):
    """Walks one def (loop or pool context).  In pool context, a call
    to a loop-only name or a loop-affine primitive is a finding unless
    it rides a threadsafe seam.  Nested defs switch context: a nested
    def passed through a threadsafe seam (or async by construction)
    runs ON the loop, so its body is loop context; other nested defs
    inherit.  Nested walks are deferred to `finish()` so a thunk
    scheduled *below* its def still gets loop context."""

    def __init__(self, model: ModuleModel, loop_only_names: Set[str],
                 findings: List[Finding], in_loop: bool,
                 by_node: Dict[int, FunctionInfo]):
        self.model = model
        self.loop_only = loop_only_names
        self.findings = findings
        self.in_loop = in_loop
        self.by_node = by_node
        # Names of local defs scheduled onto the loop via a seam.
        self.loop_thunks: Set[str] = set()
        self._deferred: List[ast.AST] = []

    def visit_Call(self, node: ast.Call) -> None:
        seg = last_segment(node.func)
        if seg in _THREADSAFE_SEGS:
            # Everything inside the seam's thunk runs on the loop; mark
            # fn-reference names so their defs get loop context.  The
            # seam call itself is legal from anywhere.
            for a in node.args:
                n = last_segment(a)
                if n:
                    self.loop_thunks.add(n)
                self.visit(a)
            for kw in node.keywords:
                self.visit(kw.value)
            return
        if not self.in_loop:
            if seg in self.loop_only:
                self.findings.append(Finding(
                    "loop-affinity", self.model.relpath, node.lineno,
                    f"loop-only '{seg}' called from pool/thread "
                    f"context without the call_soon_threadsafe seam"))
            elif seg in _LOOP_AFFINE_SEGS and \
                    root_segment(node.func) != "asyncio" and \
                    _looks_like_loop_receiver(node.func):
                self.findings.append(Finding(
                    "loop-affinity", self.model.relpath, node.lineno,
                    f"loop-affine '{seg}' used from pool/thread "
                    f"context; route it through call_soon_threadsafe"))
            elif seg == "set_result" and \
                    _looks_like_future_receiver(node.func):
                self.findings.append(Finding(
                    "loop-affinity", self.model.relpath, node.lineno,
                    "Future.set_result from pool/thread context; use "
                    "loop.call_soon_threadsafe(fut.set_result, ...)"))
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._deferred.append(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._deferred.append(node)

    def finish(self) -> None:
        """Walk deferred nested defs with their resolved context."""
        for nested in self._deferred:
            ninfo = self.by_node.get(id(nested))
            nested_loop = (
                self.in_loop
                or isinstance(nested, ast.AsyncFunctionDef)
                or getattr(nested, "name", "") in self.loop_thunks
                or (ninfo is not None and ninfo.loop_only))
            sub = _AffinityVisitor(self.model, self.loop_only,
                                   self.findings, nested_loop,
                                   self.by_node)
            for stmt in nested.body:
                sub.visit(stmt)
            sub.finish()


def _looks_like_loop_receiver(func: ast.AST) -> bool:
    """`<...>.loop.call_later` / `loop.create_task` — receiver chain
    mentions a loop."""
    node = func
    while isinstance(node, ast.Attribute):
        node = node.value
        if isinstance(node, ast.Attribute) and "loop" in node.attr:
            return True
        if isinstance(node, ast.Name) and "loop" in node.id:
            return True
    return False


def _looks_like_future_receiver(func: ast.AST) -> bool:
    if not isinstance(func, ast.Attribute):
        return False
    recv = func.value
    seg = last_segment(recv)
    return seg is not None and ("future" in seg.lower()
                                or seg.lower() in ("fut", "f"))


def _check_loop_affinity(model: ModuleModel,
                         functions: List[FunctionInfo],
                         loop_only_names: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    by_node: Dict[int, FunctionInfo] = {
        id(info.node): info for info in functions
        if info.node is not None}

    # Only walk outermost defs/methods directly; nested defs are walked
    # by finish() so seam-scheduled thunks get loop context.
    seen_nested: Set[int] = set()
    for info in functions:
        if info.node is None or id(info.node) in seen_nested:
            continue
        for sub in ast.walk(info.node):
            if sub is not info.node and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                seen_nested.add(id(sub))
        v = _AffinityVisitor(model, loop_only_names, findings,
                             _loop_context_def(info.node, info), by_node)
        for stmt in info.node.body:
            v.visit(stmt)
        v.finish()
    return findings


# ---------------------------------------------------------------------------
# async-lifecycle: timer handles and task objects.
# ---------------------------------------------------------------------------


class _LifecycleChecker:
    """Per-def: every call_later/create_task result must be retained;
    locally-retained handles must be cancelled, awaited, returned, or
    stored before every exit."""

    def __init__(self, model: ModuleModel, func: ast.AST,
                 findings: List[Finding]):
        self.model = model
        self.func = func
        self.findings = findings
        # name -> ("timer"|"task", lineno); removed once settled.
        self.live: Dict[str, Tuple[str, int]] = {}

    _RULE = {"timer": "async-timer-leak", "task": "async-task-orphan"}
    _WHAT = {"timer": "call_later handle", "task": "asyncio task"}

    def _producer_kind(self, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        seg = last_segment(node.func)
        if seg in _TIMER_SEGS:
            return "timer"
        if seg in _TASK_SEGS:
            return "task"
        return None

    def _settle(self, name: str) -> None:
        self.live.pop(name, None)

    def run(self) -> None:
        self._walk(self.func.body)
        # Handles still live at the natural end of the def never get
        # cancelled on this path.
        for name, (kind, line) in self.live.items():
            self.findings.append(Finding(
                self._RULE[kind], self.model.relpath, line,
                f"{self._WHAT[kind]} '{name}' in "
                f"{getattr(self.func, 'name', '<lambda>')} is never "
                f"cancelled, awaited, or handed off on some path"))

    def _walk(self, stmts: Sequence[ast.AST]) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # checked as their own defs
        if isinstance(node, ast.Expr):
            kind = self._producer_kind(node.value)
            if kind is not None:
                seg = last_segment(node.value.func)
                self.findings.append(Finding(
                    self._RULE[kind], self.model.relpath,
                    node.value.lineno,
                    f"{seg}(...) result dropped: the "
                    f"{self._WHAT[kind]} can never be cancelled"))
                return
            self._expr_effects(node.value)
            return
        if isinstance(node, ast.Assign):
            kind = self._producer_kind(node.value)
            if kind is not None and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    self.live[tgt.id] = (kind, node.value.lineno)
                    return
                # self.X = call_later(...) — stored: owner's lifecycle.
                return
            self._expr_effects(node.value)
            # Reassignment of a live name loses the old handle — but a
            # common idiom re-arms (timer = call_later again after
            # cancel); keep it simple: reassignment settles.
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self._settle(tgt.id)
                elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    # handle stored somewhere: transfer.
                    if isinstance(node.value, ast.Name):
                        self._settle(node.value.id)
            return
        if isinstance(node, ast.Return):
            if node.value is not None:
                self._expr_effects(node.value)
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Name):
                        self._settle(sub.id)
            return
        if isinstance(node, ast.Try):
            self._walk(node.body)
            for h in node.handlers:
                self._walk(h.body)
            self._walk(node.orelse)
            self._walk(node.finalbody)
            return
        if isinstance(node, (ast.If, ast.While)):
            self._expr_effects(node.test)
            self._walk(node.body)
            self._walk(node.orelse)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._expr_effects(node.iter)
            self._walk(node.body)
            self._walk(node.orelse)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self._expr_effects(item.context_expr)
            self._walk(node.body)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child)
            else:
                self._expr_effects(child)

    def _expr_effects(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Await):
                if isinstance(sub.value, ast.Name):
                    self._settle(sub.value.id)
                continue
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            # handle.cancel() settles; await task settles via Await.
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.attr in _SETTLE_SEGS:
                self._settle(f.value.id)
            # fn(handle) / container.append(handle): hand-off.
            for a in list(sub.args) + [kw.value for kw in sub.keywords]:
                if isinstance(a, ast.Name):
                    self._settle(a.id)
                # A seam thunk whose *body is* the producer call throws
                # the handle away: call_soon(lambda: loop.call_later(
                # ...)) — the lambda's return value is discarded by the
                # loop, so nothing can ever cancel the timer.
                if isinstance(a, ast.Lambda):
                    kind = self._producer_kind(a.body)
                    if kind is not None:
                        seg = last_segment(a.body.func)
                        self.findings.append(Finding(
                            self._RULE[kind], self.model.relpath,
                            a.body.lineno,
                            f"{seg}(...) handle discarded by the "
                            f"scheduling thunk: the {self._WHAT[kind]} "
                            f"can never be cancelled"))


def _check_async_lifecycle(model: ModuleModel,
                           functions: List[FunctionInfo]
                           ) -> List[Finding]:
    findings: List[Finding] = []
    for info in functions:
        if info.node is None:
            continue
        # Each def is checked independently; nested defs are their own
        # entries in `functions`, so no double-walk guard is needed —
        # _stmt skips nested defs.
        _LifecycleChecker(model, info.node, findings).run()
    return findings
