"""Rule family 4: jit hygiene inside @jax.jit functions (ops/, parallel/).

Three checks:

* ``jit-nondet`` — wall-clock / RNG / uuid calls inside a jitted body.
  They execute once at trace time and bake a constant into the
  compiled executable; every later call silently reuses it.
* ``jit-tracer-if`` — a Python ``if``/``while``/ternary whose test
  mentions a *traced* parameter.  Under jit the test runs on a tracer
  and raises TracerBoolConversionError at runtime — or worse, on a
  weakly-typed value it silently specializes.  Shape/dtype probes
  (``x.shape``, ``x.ndim``, ``len(x)``, ``isinstance``, ``x is None``)
  are static and exempt.
* ``jit-static-unhashable`` — a list/dict/set bound to a
  ``static_argnames`` parameter (default value or module-local call
  site).  Static args key the compilation cache and must be hashable.

Detection is conservative (direct parameter mentions only; closures
and derived locals are not tracked) — false negatives over false
positives, like the lock rules.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (
    AnalyzerConfig,
    Finding,
    ModuleModel,
    _dotted,
    last_segment,
    root_segment,
)
from .lockrules import _in_scope

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "weak_type",
                 "sharding", "itemsize", "nbytes"}
_STATIC_CALLS = {"len", "isinstance", "issubclass", "getattr", "hasattr",
                 "callable", "type"}

_NONDET_ROOTS = {"random", "secrets", "uuid"}
_NONDET_DOTTED = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "time.monotonic_ns", "time.perf_counter_ns", "os.urandom",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
}
_NONDET_PREFIXES = ("np.random.", "numpy.random.")

_UNHASHABLE_NODES = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)


def _is_jit_expr(node: ast.AST) -> bool:
    """`jit` / `jax.jit` as a bare expression."""
    seg = last_segment(node)
    if seg != "jit":
        return False
    root = root_segment(node)
    return root in ("jax", "jit")


def _static_names_from_call(call: ast.Call) -> Set[str]:
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for el in v.elts:
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, str):
                        names.add(el.value)
    return names


def _static_nums_from_call(call: ast.Call) -> Set[int]:
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for el in v.elts:
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, int):
                        nums.add(el.value)
    return nums


def _jit_spec_from_decorator(deco: ast.AST
                             ) -> Optional[Tuple[Set[str], Set[int]]]:
    """(static_argnames, static_argnums) when `deco` marks a jit."""
    if _is_jit_expr(deco):
        return set(), set()
    if isinstance(deco, ast.Call):
        # @jax.jit(...) directly.
        if _is_jit_expr(deco.func):
            return _static_names_from_call(deco), _static_nums_from_call(deco)
        # @functools.partial(jax.jit, static_argnames=...).
        if last_segment(deco.func) == "partial" and deco.args and \
                _is_jit_expr(deco.args[0]):
            return _static_names_from_call(deco), _static_nums_from_call(deco)
    return None


def _collect_jitted(tree: ast.Module
                    ) -> List[Tuple[ast.FunctionDef, Set[str]]]:
    """All jitted defs with their static parameter-name sets."""
    defs_by_name: Dict[str, List[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs_by_name.setdefault(node.name, []).append(node)

    out: List[Tuple[ast.FunctionDef, Set[str]]] = []
    seen: Set[int] = set()

    def add(fn: ast.FunctionDef, names: Set[str], nums: Set[int]) -> None:
        if id(fn) in seen:
            return
        seen.add(id(fn))
        params = [a.arg for a in (fn.args.posonlyargs + fn.args.args)]
        static = set(names)
        for i in nums:
            if 0 <= i < len(params):
                static.add(params[i])
        out.append((fn, static))

    for fn_list in defs_by_name.values():
        for fn in fn_list:
            for deco in fn.decorator_list:
                spec = _jit_spec_from_decorator(deco)
                if spec is not None:
                    add(fn, *spec)
                    break
    # `g = jax.jit(fn, ...)` / `return jax.jit(fn)` over a local def.
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node.func) and \
                node.args and isinstance(node.args[0], ast.Name):
            for fn in defs_by_name.get(node.args[0].id, []):
                add(fn, _static_names_from_call(node),
                    _static_nums_from_call(node))
    return out


def _mentions_traced(node: ast.AST, traced: Set[str]) -> bool:
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return False
    if isinstance(node, ast.Call) and \
            last_segment(node.func) in _STATIC_CALLS:
        return False
    if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
        return False
    if isinstance(node, ast.Name):
        return node.id in traced
    return any(_mentions_traced(c, traced)
               for c in ast.iter_child_nodes(node))


def _check_body(model: ModuleModel, fn: ast.FunctionDef,
                static: Set[str], findings: List[Finding]) -> None:
    params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                              + fn.args.kwonlyargs)}
    traced = params - static - {"self"}

    # Unhashable defaults on static params.
    pos = fn.args.posonlyargs + fn.args.args
    for arg, default in zip(pos[len(pos) - len(fn.args.defaults):],
                            fn.args.defaults):
        if arg.arg in static and isinstance(default, _UNHASHABLE_NODES):
            findings.append(Finding(
                "jit-static-unhashable", model.relpath, default.lineno,
                f"static arg '{arg.arg}' of {fn.name} defaults to an "
                f"unhashable literal (jit cache keys must hash)"))

    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            continue  # nested defs get their own entry if jitted
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func) or ""
            root = root_segment(node.func)
            if (dotted in _NONDET_DOTTED
                    or root in _NONDET_ROOTS
                    or any(dotted.startswith(p)
                           for p in _NONDET_PREFIXES)):
                findings.append(Finding(
                    "jit-nondet", model.relpath, node.lineno,
                    f"{dotted or root} inside @jit {fn.name}: traced "
                    f"once, the value is baked into the executable"))
        test = None
        if isinstance(node, (ast.If, ast.While)):
            test = node.test
        elif isinstance(node, ast.IfExp):
            test = node.test
        if test is not None and _mentions_traced(test, traced):
            findings.append(Finding(
                "jit-tracer-if", model.relpath, test.lineno,
                f"Python branch on traced argument inside @jit "
                f"{fn.name}: use jnp.where/lax.cond or mark the arg "
                f"static"))


def _check_call_sites(model: ModuleModel,
                      jitted: List[Tuple[ast.FunctionDef, Set[str]]],
                      findings: List[Finding]) -> None:
    by_name = {fn.name: static for fn, static in jitted if static}
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        name = None
        if isinstance(node.func, ast.Name):
            name = node.func.id
        static = by_name.get(name or "")
        if not static:
            continue
        for kw in node.keywords:
            if kw.arg in static and isinstance(kw.value, _UNHASHABLE_NODES):
                findings.append(Finding(
                    "jit-static-unhashable", model.relpath,
                    kw.value.lineno,
                    f"unhashable literal passed for static arg "
                    f"'{kw.arg}' of {name}"))


def check_module(model: ModuleModel,
                 config: AnalyzerConfig) -> List[Finding]:
    if not _in_scope(model.relpath, config.jit_path_fragments):
        return []
    findings: List[Finding] = []
    jitted = _collect_jitted(model.tree)
    for fn, static in jitted:
        _check_body(model, fn, static, findings)
    _check_call_sites(model, jitted, findings)
    return findings
