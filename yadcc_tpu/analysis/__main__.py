"""CLI: ``python -m yadcc_tpu.analysis [paths...]``.

Exit status: 0 = clean (no unsuppressed findings), 1 = findings,
2 = usage error.  ``make lint`` runs this over ``yadcc_tpu/``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import minitoml
from .core import RULES, AnalyzerConfig, analyze_paths

_DEFAULT_HIERARCHY = os.path.join(os.path.dirname(__file__),
                                  "lock_hierarchy.toml")


def _load_ranks(path: str) -> dict:
    doc = minitoml.load_path(path)
    ranks = doc.get("rank", {})
    bad = {k: v for k, v in ranks.items() if not isinstance(v, int)}
    if bad:
        raise minitoml.MiniTomlError(
            f"[rank] values must be integers: {sorted(bad)}")
    return dict(ranks)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m yadcc_tpu.analysis",
        description="AST-based concurrency & jit-discipline analyzer "
                    "(doc/static_analysis.md)")
    ap.add_argument("paths", nargs="*", default=["yadcc_tpu"],
                    help="files or directories to analyze "
                         "(default: yadcc_tpu)")
    ap.add_argument("--hierarchy", default=_DEFAULT_HIERARCHY,
                    help="lock hierarchy TOML (default: the package's "
                         "lock_hierarchy.toml)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the findings report to this path")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings")
    ap.add_argument("--strict-suppressions", action="store_true",
                    help="fail on suppressions that matched nothing")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule:24s} {desc}")
        return 0

    try:
        ranks = _load_ranks(args.hierarchy)
    except (OSError, minitoml.MiniTomlError) as e:
        print(f"cannot load lock hierarchy {args.hierarchy}: {e}",
              file=sys.stderr)
        return 2
    for p in args.paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    config = AnalyzerConfig(
        lock_ranks=ranks,
        strict_suppressions=args.strict_suppressions)
    findings, stats = analyze_paths(args.paths, config)

    shown = 0
    for f in findings:
        if f.suppressed and not args.show_suppressed:
            continue
        tag = " (suppressed)" if f.suppressed else ""
        print(f.render() + tag)
        shown += 1
    print(f"ytpu-analyze: {stats['files_analyzed']} files, "
          f"{stats['findings']} finding(s), "
          f"{stats['suppressed']} suppressed")

    if args.json_out:
        report = {
            "version": 1,
            "stats": stats,
            "findings": [f.as_dict() for f in findings],
        }
        with open(args.json_out, "w", encoding="utf-8") as fp:
            json.dump(report, fp, indent=2, sort_keys=True)
            fp.write("\n")

    return 1 if stats["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
