"""CLI: ``python -m yadcc_tpu.analysis [paths...]``.

Exit status: 0 = clean (no unsuppressed findings), 1 = findings,
2 = usage error.  ``make lint`` runs this over ``yadcc_tpu/``.

Incremental-rollout / performance surface:

    --baseline FILE         ignore findings recorded in FILE
    --write-baseline FILE   record current findings and exit 0
    --stats                 per-rule-family timing + cache hit rate
    --sarif FILE            SARIF 2.1.0 report (CI code annotations)
    --no-cache / --cache P  content-hash result cache control
    --wire-golden FILE      golden wire descriptor (default: packaged)
    --update-wire-golden    re-pin the golden from api/gen and exit
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import minitoml, wirecompat
from .core import RULES, AnalyzerConfig, analyze_paths, baseline_key

_DEFAULT_HIERARCHY = os.path.join(os.path.dirname(__file__),
                                  "lock_hierarchy.toml")
_DEFAULT_GOLDEN = os.path.join(os.path.dirname(__file__),
                               "wire_golden.json")


def _load_ranks(path: str) -> dict:
    doc = minitoml.load_path(path)
    ranks = doc.get("rank", {})
    bad = {k: v for k, v in ranks.items() if not isinstance(v, int)}
    if bad:
        raise minitoml.MiniTomlError(
            f"[rank] values must be integers: {sorted(bad)}")
    return dict(ranks)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m yadcc_tpu.analysis",
        description="AST-based concurrency, jit-discipline, taint, "
                    "resource-lifecycle and wire-compat analyzer "
                    "(doc/static_analysis.md)")
    ap.add_argument("paths", nargs="*", default=["yadcc_tpu"],
                    help="files or directories to analyze "
                         "(default: yadcc_tpu)")
    ap.add_argument("--hierarchy", default=_DEFAULT_HIERARCHY,
                    help="lock hierarchy TOML (default: the package's "
                         "lock_hierarchy.toml)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the findings report to this path")
    ap.add_argument("--sarif", dest="sarif_out", default=None,
                    help="write a SARIF 2.1.0 report to this path "
                         "(CI annotations)")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings")
    ap.add_argument("--strict-suppressions", action="store_true",
                    help="fail on suppressions that matched nothing")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--baseline", default=None,
                    help="ignore findings recorded in this file "
                         "(incremental rollout)")
    ap.add_argument("--write-baseline", default=None,
                    help="record current unsuppressed findings to this "
                         "file and exit 0")
    ap.add_argument("--stats", action="store_true",
                    help="print per-rule-family timings and cache "
                         "hit rate")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the content-hash result cache")
    ap.add_argument("--cache", dest="cache_path", default=None,
                    help="result cache location (default: "
                         "~/.cache/ytpu-analyze/cache.json)")
    ap.add_argument("--wire-golden", default=None,
                    help="golden wire descriptor JSON (default: the "
                         "package's analysis/wire_golden.json when it "
                         "exists)")
    ap.add_argument("--update-wire-golden", action="store_true",
                    help="re-pin the golden descriptor from the "
                         "analyzed tree's api/gen modules and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule:24s} {desc}")
        return 0

    try:
        ranks = _load_ranks(args.hierarchy)
    except (OSError, minitoml.MiniTomlError) as e:
        print(f"cannot load lock hierarchy {args.hierarchy}: {e}",
              file=sys.stderr)
        return 2
    for p in args.paths:
        if not os.path.exists(p):
            print(f"no such path: {p}", file=sys.stderr)
            return 2

    if args.update_wire_golden:
        api_dirs = wirecompat.find_api_dirs(args.paths)
        if not api_dirs:
            print("no api/protos tree under the analyzed paths",
                  file=sys.stderr)
            return 2
        golden = wirecompat.build_golden(api_dirs)
        out = args.wire_golden or _DEFAULT_GOLDEN
        with open(out, "w", encoding="utf-8") as fp:
            json.dump(golden, fp, indent=1, sort_keys=True)
            fp.write("\n")
        print(f"pinned {sum(len(v['messages']) for v in golden.values())}"
              f" messages across {len(golden)} protos into {out}")
        return 0

    wire_golden = args.wire_golden
    if wire_golden is None and os.path.exists(_DEFAULT_GOLDEN):
        wire_golden = _DEFAULT_GOLDEN

    config = AnalyzerConfig(
        lock_ranks=ranks,
        strict_suppressions=args.strict_suppressions,
        wire_golden=wire_golden)

    cache = None
    if not args.no_cache:
        from .cache import ResultCache

        cache = ResultCache(args.cache_path)
    findings, stats = analyze_paths(args.paths, config, cache=cache)
    if cache is not None:
        cache.save()

    if args.write_baseline:
        keys = sorted({baseline_key(f) for f in findings
                       if not f.suppressed})
        with open(args.write_baseline, "w", encoding="utf-8") as fp:
            fp.write("\n".join(keys) + ("\n" if keys else ""))
        print(f"wrote {len(keys)} baseline entr"
              f"{'y' if len(keys) == 1 else 'ies'} to "
              f"{args.write_baseline}")
        return 0

    baselined = 0
    if args.baseline:
        try:
            with open(args.baseline, "r", encoding="utf-8") as fp:
                allow = {line.strip() for line in fp if line.strip()}
        except OSError as e:
            print(f"cannot load baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        for f in findings:
            if not f.suppressed and baseline_key(f) in allow:
                f.suppressed = True
                baselined += 1
        stats["findings"] -= baselined
        stats["suppressed"] += baselined
    stats["baselined"] = baselined

    for f in findings:
        if f.suppressed and not args.show_suppressed:
            continue
        tag = " (suppressed)" if f.suppressed else ""
        print(f.render() + tag)
    line = (f"ytpu-analyze: {stats['files_analyzed']} files, "
            f"{stats['findings']} finding(s), "
            f"{stats['suppressed']} suppressed")
    if baselined:
        line += f" ({baselined} baselined)"
    print(line)

    if args.stats:
        print(f"cache: {stats['cache_hits']}/{stats['files_analyzed']} "
              f"file hits")
        for name, secs in sorted(stats["timings"].items(),
                                 key=lambda kv: -kv[1]):
            print(f"  {name:16s} {secs * 1000:8.1f} ms")

    if args.json_out:
        report = {
            "version": 2,
            "stats": stats,
            "findings": [f.as_dict() for f in findings],
        }
        with open(args.json_out, "w", encoding="utf-8") as fp:
            json.dump(report, fp, indent=2, sort_keys=True)
            fp.write("\n")

    if args.sarif_out:
        from . import sarif

        with open(args.sarif_out, "w", encoding="utf-8") as fp:
            json.dump(sarif.to_sarif(findings), fp, indent=2,
                      sort_keys=True)
            fp.write("\n")

    return 1 if stats["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
