"""SARIF 2.1.0 export for ytpu-analyze findings.

Minimal single-run document: one ``run`` whose driver lists every rule
in the catalog and whose ``results`` carry one entry per finding.
Suppressed findings are exported with a ``suppressions`` entry (SARIF's
own notion) so CI annotation surfaces can show-or-hide them without
re-running the analyzer; unsuppressed findings are plain ``error``
results.  Round-trip fidelity (rule id, path, line, message,
suppression state) is pinned by tests/test_asyncproto.py.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from .core import RULES, Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")
_TOOL_NAME = "ytpu-analyze"


def to_sarif(findings: Sequence[Finding],
             tool_version: str = "3.0") -> Dict:
    """Findings -> SARIF 2.1.0 document (a plain JSON-ready dict)."""
    rules = [{
        "id": rule,
        "shortDescription": {"text": desc},
    } for rule, desc in sorted(RULES.items())]
    results: List[Dict] = []
    for f in findings:
        result: Dict = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line},
                },
            }],
        }
        if f.suppressed:
            result["suppressions"] = [{"kind": "inSource"}]
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": _TOOL_NAME,
                "version": tool_version,
                "informationUri":
                    "doc/static_analysis.md",
                "rules": rules,
            }},
            "results": results,
        }],
    }


def from_sarif(doc: Dict) -> List[Finding]:
    """SARIF document -> findings (the round-trip test's other half,
    and the hook for diffing two CI runs' annotation sets)."""
    findings: List[Finding] = []
    for run in doc.get("runs", ()):
        for result in run.get("results", ()):
            locs = result.get("locations") or [{}]
            phys = locs[0].get("physicalLocation", {})
            findings.append(Finding(
                rule=result.get("ruleId", "?"),
                path=phys.get("artifactLocation", {}).get("uri", "?"),
                line=phys.get("region", {}).get("startLine", 0),
                message=result.get("message", {}).get("text", ""),
                suppressed=bool(result.get("suppressions")),
            ))
    return findings
