"""Rule family 7: wire-format compatibility.

The dataplane parity gates (tools/ci.sh ``--smoke``) prove the bytes
on the wire and in the cache are identical across refactors — but only
for the code paths the smoke drives.  This family checks the *schema*
itself, statically, in three layers:

* ``wire-drift`` — ``api/protos/*.proto`` (the human-readable source
  of truth) is cross-checked field-for-field against the committed
  ``api/gen/*_pb2.py`` descriptors (parsed out of the
  ``AddSerializedFile`` blob — the gen module is never imported, so
  the check cannot collide with an already-loaded descriptor pool).
  A field added to the text but not regenerated, or a gen module
  hand-edited out from under its proto, fails lint.
* ``wire-golden`` — the committed golden descriptor
  (``analysis/wire_golden.json``) pins every message/field/enum
  number.  Removing or renumbering a field breaks every peer and every
  existing cache entry (keys and entry bodies embed serialized
  messages), so it must fail lint *before* it fails in production.
  Additions are flagged too: extending the wire format is legal but
  must be an explicit act — ``python -m yadcc_tpu.analysis
  --update-wire-golden`` refreshes the pin after review.
* ``wire-unknown-field`` — constructor keyword arguments on message
  classes (``api.daemon.HeartbeatRequest(tokn=...)``) and repeated-
  field ``.add(...)`` calls are checked against the descriptor's field
  names, catching the typo'd-field class of bug that proto3's
  permissive ``ignore_unknown_fields`` JSON path would silently drop.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import AnalyzerConfig, Finding

# descriptor_pb2 FieldDescriptorProto.Type -> canonical name.
_TYPE_NAMES = {
    1: "double", 2: "float", 3: "int64", 4: "uint64", 5: "int32",
    8: "bool", 9: "string", 12: "bytes", 13: "uint32",
    11: "message", 14: "enum",
}

_FIELD_RE = re.compile(
    r"^\s*(repeated\s+)?([\w.]+)\s+(\w+)\s*=\s*(\d+)\s*;")
_ENUM_VALUE_RE = re.compile(r"^\s*(\w+)\s*=\s*(\d+)\s*;")
_BLOCK_RE = re.compile(r"^\s*(message|enum|service)\s+(\w+)\s*\{?")

_SCALARS = {"double", "float", "int32", "int64", "uint32", "uint64",
            "sint32", "sint64", "fixed32", "fixed64", "sfixed32",
            "sfixed64", "bool", "string", "bytes"}


# ---------------------------------------------------------------------------
# Parsers.
# ---------------------------------------------------------------------------


def parse_proto_text(path: str) -> dict:
    """{"messages": {name: {field: [number, type, label]}},
    "enums": {name: {value: number}}, "lines": {...}} from .proto text.
    Covers the subset this repo uses: flat proto3 messages/enums, no
    nesting, no oneof/map."""
    messages: Dict[str, Dict[str, list]] = {}
    enums: Dict[str, Dict[str, int]] = {}
    lines_idx: Dict[str, int] = {}
    stack: List[Tuple[str, str]] = []
    with open(path, "r", encoding="utf-8") as fp:
        for lineno, raw in enumerate(fp, start=1):
            line = raw.split("//", 1)[0].rstrip()
            if not line.strip():
                continue
            m = _BLOCK_RE.match(line)
            if m:
                kind, name = m.group(1), m.group(2)
                stack.append((kind, name))
                if kind == "message":
                    messages.setdefault(name, {})
                elif kind == "enum":
                    enums.setdefault(name, {})
                # One-liner `message Foo {}`:
                if "{" in line and "}" in line:
                    stack.pop()
                continue
            if "}" in line and stack:
                stack.pop()
                continue
            if not stack:
                continue
            kind, name = stack[-1]
            if kind == "message":
                fm = _FIELD_RE.match(line)
                if fm:
                    label = "repeated" if fm.group(1) else ""
                    ftype = fm.group(2).split(".")[-1]
                    if ftype not in _SCALARS:
                        # Message vs enum reference resolved at compare
                        # time; record the bare type name.
                        pass
                    messages[name][fm.group(3)] = [int(fm.group(4)),
                                                   ftype, label]
                    lines_idx[f"{name}.{fm.group(3)}"] = lineno
            elif kind == "enum":
                em = _ENUM_VALUE_RE.match(line)
                if em:
                    enums[name][em.group(1)] = int(em.group(2))
    return {"messages": messages, "enums": enums, "lines": lines_idx}


def extract_serialized_descriptor(gen_path: str) -> Optional[bytes]:
    """The AddSerializedFile(b'...') blob from a *_pb2.py, via AST —
    the module is never imported (importing would register into the
    process-global descriptor pool and conflict with the package's own
    already-loaded copy)."""
    try:
        with open(gen_path, "r", encoding="utf-8") as fp:
            tree = ast.parse(fp.read())
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "AddSerializedFile" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, bytes):
            return node.args[0].value
    return None


def parse_gen_descriptor(gen_path: str) -> Optional[dict]:
    """Same shape as parse_proto_text, from the committed descriptor."""
    blob = extract_serialized_descriptor(gen_path)
    if blob is None:
        return None
    try:
        from google.protobuf import descriptor_pb2
    except ImportError:
        return None
    fd = descriptor_pb2.FileDescriptorProto()
    try:
        fd.ParseFromString(blob)
    except Exception:
        return None
    messages: Dict[str, Dict[str, list]] = {}
    enums: Dict[str, Dict[str, int]] = {}
    for msg in fd.message_type:
        fields: Dict[str, list] = {}
        for f in msg.field:
            tname = _TYPE_NAMES.get(f.type, str(f.type))
            if tname in ("message", "enum"):
                tname = f.type_name.split(".")[-1]
            fields[f.name] = [f.number, tname,
                              "repeated" if f.label == 3 else ""]
        messages[msg.name] = fields
    for en in fd.enum_type:
        enums[en.name] = {v.name: v.number for v in en.value}
    return {"name": fd.name, "messages": messages, "enums": enums}


# ---------------------------------------------------------------------------
# API-tree discovery.
# ---------------------------------------------------------------------------


def find_api_dirs(paths: Sequence[str], max_depth: int = 3) -> List[str]:
    """Directories named api/ holding protos/ under any analyzed root."""
    out: List[str] = []
    seen: Set[str] = set()
    for p in paths:
        if os.path.isfile(p):
            p = os.path.dirname(p)
        base_depth = os.path.abspath(p).count(os.sep)
        for dirpath, dirnames, _ in os.walk(p):
            if os.path.abspath(dirpath).count(os.sep) - base_depth \
                    > max_depth:
                dirnames[:] = []
                continue
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            if os.path.basename(dirpath) == "api" and \
                    os.path.isdir(os.path.join(dirpath, "protos")):
                ap = os.path.abspath(dirpath)
                if ap not in seen:
                    seen.add(ap)
                    out.append(dirpath)
    return out


# ---------------------------------------------------------------------------
# Checks.
# ---------------------------------------------------------------------------


def _rel(api_dir: str, *parts: str) -> str:
    return os.path.join(os.path.basename(os.path.dirname(api_dir))
                        or "api", "api", *parts).replace(os.sep, "/")


def _compare_schema(proto_rel: str, text: dict, gen: dict,
                    findings: List[Finding]) -> None:
    lines = text.get("lines", {})

    def line_of(msg: str, fld: str = "") -> int:
        return lines.get(f"{msg}.{fld}", 1)

    for mname, tfields in text["messages"].items():
        gfields = gen["messages"].get(mname)
        if gfields is None:
            findings.append(Finding(
                "wire-drift", proto_rel, 1,
                f"message {mname} missing from committed gen module "
                f"(regenerate: python -m yadcc_tpu.api.build_protos)"))
            continue
        for fname, (num, ftype, label) in tfields.items():
            g = gfields.get(fname)
            if g is None:
                findings.append(Finding(
                    "wire-drift", proto_rel, line_of(mname, fname),
                    f"{mname}.{fname} missing from committed gen "
                    f"module (regenerate)"))
            elif g[0] != num:
                findings.append(Finding(
                    "wire-drift", proto_rel, line_of(mname, fname),
                    f"{mname}.{fname}: proto says field number {num}, "
                    f"gen module says {g[0]}"))
            elif g[1] != ftype or g[2] != label:
                findings.append(Finding(
                    "wire-drift", proto_rel, line_of(mname, fname),
                    f"{mname}.{fname}: proto says "
                    f"{label + ' ' if label else ''}{ftype}, gen "
                    f"module says "
                    f"{g[2] + ' ' if g[2] else ''}{g[1]}"))
        for fname in gfields:
            if fname not in tfields:
                findings.append(Finding(
                    "wire-drift", proto_rel, 1,
                    f"{mname}.{fname} exists in the gen module but "
                    f"not in the proto source"))
    for mname in gen["messages"]:
        if mname not in text["messages"]:
            findings.append(Finding(
                "wire-drift", proto_rel, 1,
                f"message {mname} exists in the gen module but not "
                f"in the proto source"))
    for ename, tvals in text["enums"].items():
        gvals = gen["enums"].get(ename)
        if gvals is None:
            findings.append(Finding(
                "wire-drift", proto_rel, 1,
                f"enum {ename} missing from committed gen module"))
            continue
        for vname, num in tvals.items():
            if vname not in gvals:
                findings.append(Finding(
                    "wire-drift", proto_rel, 1,
                    f"{ename}.{vname} missing from gen module"))
            elif gvals[vname] != num:
                findings.append(Finding(
                    "wire-drift", proto_rel, 1,
                    f"{ename}.{vname}: proto says {num}, gen module "
                    f"says {gvals[vname]}"))


def _compare_golden(proto_name: str, proto_rel: str, gen: dict,
                    golden: dict, findings: List[Finding]) -> None:
    pinned = golden.get(proto_name)
    remedy = ("an addition must be pinned: review, then run "
              "python -m yadcc_tpu.analysis --update-wire-golden")
    if pinned is None:
        findings.append(Finding(
            "wire-golden", proto_rel, 1,
            f"{proto_name} is not pinned in the golden descriptor; "
            f"{remedy}"))
        return
    for mname, pfields in pinned.get("messages", {}).items():
        gfields = gen["messages"].get(mname)
        if gfields is None:
            findings.append(Finding(
                "wire-golden", proto_rel, 1,
                f"message {mname} was REMOVED (golden pins it); "
                f"removing a message breaks wire/cache compatibility"))
            continue
        for fname, pin in pfields.items():
            g = gfields.get(fname)
            if g is None:
                findings.append(Finding(
                    "wire-golden", proto_rel, 1,
                    f"{mname}.{fname} was REMOVED (golden pins "
                    f"number {pin[0]}); peers and cached entries "
                    f"still carry it"))
            elif list(g) != list(pin):
                findings.append(Finding(
                    "wire-golden", proto_rel, 1,
                    f"{mname}.{fname} changed "
                    f"{pin} -> {list(g)}: renumbering/retyping "
                    f"breaks the byte-identical wire invariant"))
        for fname in gfields:
            if fname not in pfields:
                findings.append(Finding(
                    "wire-golden", proto_rel, 1,
                    f"new field {mname}.{fname} not in golden; "
                    f"{remedy}"))
    for mname in gen["messages"]:
        if mname not in pinned.get("messages", {}):
            findings.append(Finding(
                "wire-golden", proto_rel, 1,
                f"new message {mname} not in golden; {remedy}"))
    for ename, pvals in pinned.get("enums", {}).items():
        gvals = gen["enums"].get(ename)
        if gvals is None:
            findings.append(Finding(
                "wire-golden", proto_rel, 1,
                f"enum {ename} was REMOVED (golden pins it)"))
            continue
        for vname, num in pvals.items():
            if gvals.get(vname) != num:
                findings.append(Finding(
                    "wire-golden", proto_rel, 1,
                    f"{ename}.{vname} changed/removed (golden pins "
                    f"{num}, gen has {gvals.get(vname)})"))


def build_golden(api_dirs: Sequence[str]) -> dict:
    """Golden pin from the committed gen descriptors (the authoritative
    wire shape — protoc output and pure build agree on it)."""
    golden: Dict[str, dict] = {}
    for api_dir in api_dirs:
        gen_dir = os.path.join(api_dir, "gen")
        if not os.path.isdir(gen_dir):
            continue
        for fname in sorted(os.listdir(gen_dir)):
            if not fname.endswith("_pb2.py"):
                continue
            gen = parse_gen_descriptor(os.path.join(gen_dir, fname))
            if gen is None:
                continue
            golden[gen.get("name") or fname] = {
                "messages": gen["messages"], "enums": gen["enums"]}
    return golden


def check_paths(paths: Sequence[str], records, config: AnalyzerConfig
                ) -> List[Finding]:
    findings: List[Finding] = []
    api_dirs = find_api_dirs(paths)
    golden = None
    if config.wire_golden:
        try:
            with open(config.wire_golden, "r", encoding="utf-8") as fp:
                golden = json.load(fp)
        except (OSError, ValueError) as e:
            findings.append(Finding(
                "wire-golden", config.wire_golden, 1,
                f"cannot load golden descriptor: {e} "
                f"(run --update-wire-golden)"))

    all_messages: Dict[str, Dict[str, list]] = {}
    for api_dir in api_dirs:
        proto_dir = os.path.join(api_dir, "protos")
        gen_dir = os.path.join(api_dir, "gen")
        for fname in sorted(os.listdir(proto_dir)):
            if not fname.endswith(".proto"):
                continue
            proto_rel = _rel(api_dir, "protos", fname)
            stem = fname[:-len(".proto")]
            gen_path = os.path.join(gen_dir, f"{stem}_pb2.py")
            if not os.path.exists(gen_path):
                findings.append(Finding(
                    "wire-drift", proto_rel, 1,
                    f"no committed gen module for {fname} "
                    f"(python -m yadcc_tpu.api.build_protos)"))
                continue
            text = parse_proto_text(os.path.join(proto_dir, fname))
            gen = parse_gen_descriptor(gen_path)
            if gen is None:
                findings.append(Finding(
                    "wire-drift", proto_rel, 1,
                    f"cannot extract descriptor from {stem}_pb2.py"))
                continue
            _compare_schema(proto_rel, text, gen, findings)
            if golden is not None:
                _compare_golden(fname, proto_rel, gen, golden, findings)
            for mname, fields in gen["messages"].items():
                all_messages.setdefault(mname, {}).update(fields)

    if all_messages:
        findings.extend(_check_field_access(records, all_messages))
    return findings


def _check_field_access(records, all_messages: Dict[str, Dict[str, list]]
                        ) -> List[Finding]:
    findings: List[Finding] = []
    # repeated message field name -> union of target-message field names.
    repeated_msg_fields: Dict[str, Set[str]] = {}
    for fields in all_messages.values():
        for fname, (num, ftype, label) in fields.items():
            if label == "repeated" and ftype in all_messages:
                repeated_msg_fields.setdefault(fname, set()).update(
                    all_messages[ftype])
    for rec in records:
        for site in rec.callsites:
            if site.get("tasktype"):
                continue
            last = site["last"]
            kwargs = site["kwargs"]
            if last in all_messages:
                allowed = set(all_messages[last])
                for kw in kwargs:
                    if kw not in allowed:
                        findings.append(Finding(
                            "wire-unknown-field", rec.relpath,
                            site["line"],
                            f"{last}({kw}=...): descriptor defines no "
                            f"field {kw!r}"))
            elif last == "add" and len(site.get("chain", ())) >= 2:
                parent = site["chain"][-2]
                allowed2 = repeated_msg_fields.get(parent)
                if allowed2:
                    for kw in kwargs:
                        if kw not in allowed2:
                            findings.append(Finding(
                                "wire-unknown-field", rec.relpath,
                                site["line"],
                                f"{parent}.add({kw}=...): no such "
                                f"field on the repeated message type"))
    return findings
