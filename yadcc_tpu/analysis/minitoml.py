"""Minimal TOML-subset reader for lock_hierarchy.toml.

The container's Python is 3.10 (no stdlib tomllib) and the repo policy
is zero new dependencies, so this reads exactly the subset the
hierarchy file uses: ``[section]`` headers, ``key = value`` pairs with
bare or quoted keys, integer / quoted-string values, ``#`` comments.
Anything fancier (arrays, tables-in-tables, multiline strings) is a
deliberate parse error — the hierarchy file should stay boring.
"""

from __future__ import annotations

import re
from typing import Dict

_SECTION_RE = re.compile(r"^\[\s*([A-Za-z0-9_.\-]+)\s*\]$")
_PAIR_RE = re.compile(
    r"""^(?:"([^"]+)"|'([^']+)'|([A-Za-z0-9_.\-]+))\s*=\s*(.+)$""")


class MiniTomlError(ValueError):
    pass


def _strip_comment(line: str) -> str:
    out = []
    in_str: str = ""
    for ch in line:
        if in_str:
            out.append(ch)
            if ch == in_str:
                in_str = ""
            continue
        if ch in "\"'":
            in_str = ch
            out.append(ch)
            continue
        if ch == "#":
            break
        out.append(ch)
    return "".join(out).strip()


def loads(text: str) -> Dict[str, Dict[str, object]]:
    doc: Dict[str, Dict[str, object]] = {}
    section = doc.setdefault("", {})
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = _strip_comment(raw)
        if not line:
            continue
        m = _SECTION_RE.match(line)
        if m:
            section = doc.setdefault(m.group(1), {})
            continue
        m = _PAIR_RE.match(line)
        if not m:
            raise MiniTomlError(f"line {lineno}: cannot parse {raw!r}")
        key = m.group(1) or m.group(2) or m.group(3)
        val = m.group(4).strip()
        if re.fullmatch(r"-?\d+", val):
            section[key] = int(val)
        elif len(val) >= 2 and val[0] == val[-1] and val[0] in "\"'":
            section[key] = val[1:-1]
        elif val in ("true", "false"):
            section[key] = val == "true"
        else:
            raise MiniTomlError(
                f"line {lineno}: unsupported value {val!r}")
    return doc


def load_path(path: str) -> Dict[str, Dict[str, object]]:
    with open(path, "r", encoding="utf-8") as fp:
        return loads(fp.read())
