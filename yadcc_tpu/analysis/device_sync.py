"""Rule family 7: host-blocking device syncs in dispatcher-cycle modules.

The device-resident control plane keeps the pool arrays on the
accelerator across dispatch cycles; the whole point is that a cycle
issues ONE launch and reads back only the picks, asynchronously.  A
single accidental synchronous readback — ``np.asarray(device_value)``,
``jax.device_get``, ``.block_until_ready()`` — re-serializes the
pipeline: the host stalls on the PCIe/ICI round trip every cycle and
the fused launch degenerates back into the host-loop it replaced.

``device-sync`` flags every such call in the dispatcher-cycle modules
(config.device_sync_path_fragments — filename parts, so the scope is
per-module, not per-package).  The check is syntactic: it cannot prove
the operand lives on the device, so host-side uses (``np.asarray`` over
a Python list, the sanctioned apply-boundary collect, the periodic
equivalence oracle) are expected and carry a written
``# ytpu: allow(device-sync)  # reason`` on the call line — the
pragma inventory IS the audit trail of sanctioned sync points.
Like the lock rules: false positives surface for a human decision,
silent false negatives are the failure mode we refuse.
"""

from __future__ import annotations

import ast
from typing import List

from .core import AnalyzerConfig, Finding, ModuleModel, _dotted
from .lockrules import _in_scope

# Dotted call names that force a device->host transfer (or a full
# device fence) when handed a device value.
_SYNC_DOTTED = {
    "np.asarray": "np.asarray",
    "numpy.asarray": "numpy.asarray",
    "np.array": "np.array",
    "numpy.array": "numpy.array",
    "jax.device_get": "jax.device_get",
    "jax.block_until_ready": "jax.block_until_ready",
}


def check_module(model: ModuleModel,
                 config: AnalyzerConfig) -> List[Finding]:
    if not _in_scope(model.relpath, config.device_sync_path_fragments):
        return []
    findings: List[Finding] = []
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted in _SYNC_DOTTED:
            findings.append(Finding(
                "device-sync", model.relpath, node.lineno,
                f"{_SYNC_DOTTED[dotted]} in a dispatcher-cycle module "
                f"blocks on device->host transfer when given a device "
                f"value; keep the hot loop async or annotate the "
                f"sanctioned sync point"))
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "block_until_ready"):
            findings.append(Finding(
                "device-sync", model.relpath, node.lineno,
                "block_until_ready fences the device stream inside a "
                "dispatcher-cycle module; the fused dispatch path must "
                "stay launch-and-go"))
    return findings
