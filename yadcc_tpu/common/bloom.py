"""Salted Bloom filter over a numpy bit-array.

Host-side twin of the device probe kernel in yadcc_tpu/ops/bloom_probe.py: both
sides derive probe indices identically (uint32 double hashing from a
salted xxhash64 fingerprint), so a filter built here can be shipped to
the device (or to a remote daemon, zstd-compressed) and probed there
bit-for-bit compatibly.

Parity: reference flare SaltedBloomFilter as used by
yadcc/cache/bloom_filter_generator.h:64-68 (27,584,639 bits / 10 hashes,
sized for 1M keys at 1e-5 false-positive rate) and the client-side
replica in yadcc/daemon/local/distributed_cache_reader.h:32-56.
"""

from __future__ import annotations

import sys
from typing import Iterable, List, Tuple

import numpy as np
import xxhash

from . import xxh64_np

# Same constants as the reference's generator.
DEFAULT_NUM_BITS = 27_584_639
DEFAULT_NUM_HASHES = 10

# Below this many keys the per-key C wheel call wins: the vectorized
# path pays fixed bucketing + matrix-pack overhead (~50us) that a
# handful of ~870ns digests never amortizes.  Measured crossover on the
# 1-core harness is ~40-80 keys depending on key length; 64 splits it.
VECTORIZE_MIN_KEYS = 64


def key_fingerprint(key: str, salt: int) -> Tuple[int, int]:
    """(h1, h2) uint32 pair for double hashing; h2 forced odd so the
    probe sequence cycles through the whole ring."""
    fp = xxhash.xxh64_intdigest(key.encode(), seed=salt & 0xFFFFFFFFFFFFFFFF)
    h1 = fp & 0xFFFFFFFF
    h2 = ((fp >> 32) | 1) & 0xFFFFFFFF
    return h1, h2


def _digests_loop(keys: List[bytes], seed: int) -> np.ndarray:
    """Per-key C-extension digest loop: the tiny-batch path, and the
    baseline bloom_bench measures the vectorized path against."""
    return np.fromiter(
        (xxhash.xxh64_intdigest(k, seed=seed) for k in keys),
        np.uint64, count=len(keys))


def _split_digests(dig: np.ndarray) -> np.ndarray:
    """uint64[N] digests -> [N, 2] uint32 (h1, h2), h2 forced odd —
    the ONE host-side statement of the fingerprint split (the device
    twin lives in ops/bloom_pipeline.py)."""
    if sys.byteorder == "little":
        # A little-endian u64 is already its (lo, hi) u32 pair in
        # memory: one reinterpreting copy + one in-place OR, instead
        # of two mask/shift/narrow passes over the whole batch.
        out = dig.view(np.uint32).reshape(len(dig), 2).copy()
        out[:, 1] |= 1
        return out
    out = np.empty((len(dig), 2), np.uint32)
    out[:, 0] = (dig & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    out[:, 1] = (((dig >> np.uint64(32)) | np.uint64(1))
                 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return out


def key_fingerprints_loop(keys: Iterable[str], salt: int) -> np.ndarray:
    """Per-key-loop twin of key_fingerprints; kept callable so the
    benchmark can measure the crossover the batched path is gated on."""
    if not isinstance(keys, (list, tuple)):
        keys = list(keys)
    seed = salt & 0xFFFFFFFFFFFFFFFF
    return _split_digests(_digests_loop([k.encode() for k in keys], seed))


def key_fingerprints(keys: Iterable[str], salt: int) -> np.ndarray:
    """[N, 2] uint32 fingerprint array for batched (device) probing.

    Hot path of the million-key Bloom batches (BASELINE configs[3]):
    keys are bucketed by byte length, each bucket packed into a [N, L]
    uint8 matrix and digested lane-parallel by the vectorized XXH64
    (common/xxh64_np.py) — ~30 u64 vector ops per 32-byte stripe
    amortized over the whole batch, vs ~870ns of per-key C-extension
    call overhead (round-2 bloom_bench: fingerprinting at 0.87s/1M
    keys dwarfed the 0.08s probe it fed).  Batches under
    VECTORIZE_MIN_KEYS take the per-key loop, which wins below the
    bucketing overhead's crossover."""
    if not isinstance(keys, (list, tuple)):
        keys = list(keys)
    seed = salt & 0xFFFFFFFFFFFFFFFF
    if len(keys) < VECTORIZE_MIN_KEYS:
        dig = _digests_loop([k.encode() for k in keys], seed)
    else:
        # str keys go straight to the packer — the per-key .encode()
        # list would cost a quarter of the whole vectorized budget.
        dig = xxh64_np.xxh64_keys(keys, seed)
    return _split_digests(dig)


def probe_indices_batch(fps: np.ndarray, num_hashes: int,
                        num_bits: int) -> np.ndarray:
    """[N, K] int64 probe indices for an [N, 2] fingerprint batch —
    the vectorized restatement of probe_indices (same uint32
    wrap-around then mod num_bits; keep all three in sync:
    probe_indices, this, and ops/bloom_probe.py:probe_body)."""
    i = np.arange(num_hashes, dtype=np.uint32)[None, :]
    h1 = fps[:, 0][:, None]
    h2 = fps[:, 1][:, None]
    return ((h1 + i * h2) % np.uint32(num_bits)).astype(np.int64)


def probe_indices(h1: int, h2: int, num_hashes: int, num_bits: int) -> np.ndarray:
    i = np.arange(num_hashes, dtype=np.uint32)
    # uint32 wrap-around then mod num_bits — the device kernel does the
    # exact same arithmetic, keep in sync with ops/bloom_probe.py.
    return ((np.uint32(h1) + i * np.uint32(h2)) % np.uint32(num_bits)).astype(
        np.int64
    )


class SaltedBloomFilter:
    """Bit-array Bloom filter with a per-instance salt.

    The salt makes filters from different server generations mutually
    incompatible on purpose: a client syncing against a rebuilt filter
    must do a full re-fetch rather than silently mixing bit positions.
    """

    def __init__(
        self,
        num_bits: int = DEFAULT_NUM_BITS,
        num_hashes: int = DEFAULT_NUM_HASHES,
        salt: int = 0,
        words: np.ndarray | None = None,
    ):
        self.num_bits = num_bits
        self.num_hashes = num_hashes
        self.salt = salt
        nwords = (num_bits + 31) // 32
        if words is None:
            self._words = np.zeros(nwords, dtype=np.uint32)
        else:
            # Explicit validation, not an assert: word arrays arrive
            # from the network (filter replicas), and a truncated fetch
            # must be a clean error even under `python -O`.
            if words.shape != (nwords,):
                raise ValueError(
                    f"filter data holds {words.shape[0]} words, "
                    f"{num_bits} bits needs {nwords}")
            self._words = words.astype(np.uint32, copy=False)

    # -- mutation ---------------------------------------------------------

    def add(self, key: str) -> None:
        h1, h2 = key_fingerprint(key, self.salt)
        idx = probe_indices(h1, h2, self.num_hashes, self.num_bits)
        np.bitwise_or.at(
            self._words, idx >> 5, (np.uint32(1) << (idx & 31).astype(np.uint32))
        )

    def add_many(self, keys: Iterable[str]) -> None:
        """Batched insert: one vectorized fingerprint pass, one [N, K]
        index derivation, one scatter-OR — the filter-rebuild hot path
        (a 1M-key rebuild was 1M per-key digest calls before)."""
        if not isinstance(keys, (list, tuple)):
            keys = list(keys)
        if not keys:
            return
        fps = key_fingerprints(keys, self.salt)
        idx = probe_indices_batch(fps, self.num_hashes, self.num_bits)
        np.bitwise_or.at(
            self._words, idx >> 5,
            (np.uint32(1) << (idx & 31).astype(np.uint32)))

    # -- queries ----------------------------------------------------------

    def may_contain(self, key: str) -> bool:
        h1, h2 = key_fingerprint(key, self.salt)
        idx = probe_indices(h1, h2, self.num_hashes, self.num_bits)
        bits = (self._words[idx >> 5] >> (idx & 31).astype(np.uint32)) & 1
        return bool(bits.all())

    def may_contain_batch(self, keys: Iterable[str]) -> np.ndarray:
        """bool[N] membership, fully vectorized on the host: batched
        fingerprints feed one [N, K] gather.  Bit-identical to
        may_contain per key (asserted by tests/test_bloom_fast.py)."""
        if not isinstance(keys, (list, tuple)):
            keys = list(keys)
        if not keys:
            return np.zeros(0, bool)
        fps = key_fingerprints(keys, self.salt)
        idx = probe_indices_batch(fps, self.num_hashes, self.num_bits)
        bits = (self._words[idx >> 5] >> (idx & 31).astype(np.uint32)) & 1
        return bits.all(axis=1)

    def fill_ratio(self) -> float:
        ones = int(np.unpackbits(self._words.view(np.uint8)).sum())
        return ones / (len(self._words) * 32)

    # -- (de)serialization -------------------------------------------------

    @property
    def words(self) -> np.ndarray:
        return self._words

    def to_bytes(self) -> bytes:
        return self._words.tobytes()

    @classmethod
    def from_bytes(
        cls,
        data: bytes,
        num_hashes: int,
        salt: int,
        num_bits: int | None = None,
    ) -> "SaltedBloomFilter":
        if len(data) % 4:
            raise ValueError(f"filter data length {len(data)} is not "
                             "a whole number of u32 words")
        words = np.frombuffer(data, dtype=np.uint32).copy()
        if num_bits is None:
            # The wire protocol doesn't carry num_bits (parity with the
            # reference, where it's a shared constant).  Inferring
            # len(words)*32 for arbitrary sizes would silently disagree
            # with the builder's modulus, so only the default is inferable.
            if (DEFAULT_NUM_BITS + 31) // 32 != len(words):
                raise ValueError(
                    "num_bits must be given for non-default filter sizes"
                )
            num_bits = DEFAULT_NUM_BITS
        return cls(num_bits, num_hashes, salt, words)
