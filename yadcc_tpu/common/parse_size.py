"""Human-readable byte-size parsing ("10G", "512M"), parity with
reference yadcc/common/parse_size.cc."""

from __future__ import annotations

import re
from typing import Optional

_UNITS = {
    "": 1,
    "b": 1,
    "k": 1 << 10,
    "kb": 1 << 10,
    "m": 1 << 20,
    "mb": 1 << 20,
    "g": 1 << 30,
    "gb": 1 << 30,
    "t": 1 << 40,
    "tb": 1 << 40,
}

_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*([a-zA-Z]*)\s*$")


def try_parse_size(text: str) -> Optional[int]:
    m = _RE.match(text)
    if not m:
        return None
    mult = _UNITS.get(m.group(2).lower())
    if mult is None:
        return None
    return int(float(m.group(1)) * mult)


def parse_size(text: str) -> int:
    v = try_parse_size(text)
    if v is None:
        raise ValueError(f"unrecognized size: {text!r}")
    return v
