"""Weighted consistent-hash ring, parity with reference
yadcc/common/consistent_hash.h:33-71 (100 virtual nodes per weight unit).

Two consumers with different balance requirements share this one
implementation:

* the disk cache picks a shard directory per key (the original user —
  ``vnodes_per_weight`` defaults to the reference's 100);
* the scheduler's sharded control plane routes servant heartbeats and
  grant requests shard-ward (scheduler/shard_router.py), where the
  acceptance bar is max/min key share within 1.25x across 16 shards —
  that caller passes ``SCHEDULER_VNODES_PER_WEIGHT`` (512; measured
  max/min ~1.14 on servant-id-shaped keys, vs ~1.48 at 100).

Membership is mutable: ``add_node``/``remove_node`` rebalance
incrementally with the classic consistent-hashing guarantee — removing
a node remaps ONLY the keys that node owned, adding a node steals only
the keys it now owns; every key unrelated to the change keeps its
mapping (asserted in tests/test_shard_router.py)."""

from __future__ import annotations

import bisect
from typing import Dict, List, Sequence, Tuple

import xxhash

_VNODES_PER_WEIGHT = 100

# Vnode density for shard routing (scheduler/shard_router.py): enough
# points that the max/min key share across 16 equal-weight nodes stays
# within 1.25x (doc/scheduler.md, "Sharded control plane").
SCHEDULER_VNODES_PER_WEIGHT = 512


def _hash(data: str) -> int:
    return xxhash.xxh64_intdigest(data)


class EmptyRingError(ValueError):
    """Routing against a ring with no members.

    A drained ring is a legitimate transient during failover — every
    cell of a federation can be mid-takeover at once — so callers need
    a typed error they can catch and convert into a retry/degrade
    verdict, not a bare ValueError indistinguishable from a coding
    bug.  Subclasses ValueError so pre-federation callers that caught
    that keep working."""


class ZeroWeightError(ValueError):
    """A node was added with weight <= 0 — it would own no vnodes, so
    membership would silently not mean what the caller thinks."""


class ConsistentHash:
    def __init__(self, nodes: Sequence[Tuple[str, int]],
                 vnodes_per_weight: int = _VNODES_PER_WEIGHT):
        """nodes: (name, weight) pairs; each weight unit maps to
        ``vnodes_per_weight`` virtual nodes on the ring."""
        if vnodes_per_weight <= 0:
            raise ValueError("vnodes_per_weight must be positive")
        self._vpw = vnodes_per_weight
        self._weights: Dict[str, int] = {}
        self._points: List[int] = []
        self._names: List[str] = []
        for name, weight in nodes:
            self.add_node(name, weight)

    # -- membership --------------------------------------------------------

    def add_node(self, name: str, weight: int = 1) -> None:
        """Insert (or re-weight) a node.  Keys the new vnodes now own
        move here; every other key keeps its mapping."""
        if weight <= 0:
            raise ZeroWeightError(
                f"weight must be positive: {name}={weight}")
        if name in self._weights:
            if self._weights[name] == weight:
                return
            self.remove_node(name)
        pts = sorted((_hash(f"{name}#{i}"), name)
                     for i in range(weight * self._vpw))
        merged_p: List[int] = []
        merged_n: List[str] = []
        i = j = 0
        while i < len(self._points) or j < len(pts):
            if j >= len(pts) or (i < len(self._points)
                                 and self._points[i] <= pts[j][0]):
                merged_p.append(self._points[i])
                merged_n.append(self._names[i])
                i += 1
            else:
                merged_p.append(pts[j][0])
                merged_n.append(pts[j][1])
                j += 1
        self._points = merged_p
        self._names = merged_n
        self._weights[name] = weight

    def remove_node(self, name: str) -> None:
        """Drop a node; ONLY the keys it owned remap (each to the next
        surviving point clockwise).  Unknown names are a no-op so a
        leave racing a crash-rejoin stays idempotent."""
        if name not in self._weights:
            return
        del self._weights[name]
        keep = [k for k, n in enumerate(self._names) if n != name]
        self._points = [self._points[k] for k in keep]
        self._names = [self._names[k] for k in keep]

    def nodes(self) -> Dict[str, int]:
        """Current membership: {name: weight}."""
        return dict(self._weights)

    def __len__(self) -> int:
        return len(self._weights)

    def __contains__(self, name: str) -> bool:
        return name in self._weights

    # -- lookup ------------------------------------------------------------

    def pick(self, key: str) -> str:
        if not self._points:
            raise EmptyRingError(
                "empty ring: no nodes with positive weight "
                "(membership fully drained)")
        idx = bisect.bisect_right(self._points, _hash(key))
        if idx == len(self._points):
            idx = 0
        return self._names[idx]
