"""Weighted consistent-hash ring, parity with reference
yadcc/common/consistent_hash.h:33-71 (100 virtual nodes per weight unit).
Used by the disk cache to pick a shard directory stably as shards come
and go."""

from __future__ import annotations

import bisect
from typing import List, Sequence, Tuple

import xxhash

_VNODES_PER_WEIGHT = 100


def _hash(data: str) -> int:
    return xxhash.xxh64_intdigest(data)


class ConsistentHash:
    def __init__(self, nodes: Sequence[Tuple[str, int]]):
        """nodes: (name, weight) pairs; weight units map to 100 vnodes."""
        ring: List[Tuple[int, str]] = []
        for name, weight in nodes:
            for i in range(weight * _VNODES_PER_WEIGHT):
                ring.append((_hash(f"{name}#{i}"), name))
        ring.sort()
        self._points = [p for p, _ in ring]
        self._names = [n for _, n in ring]

    def pick(self, key: str) -> str:
        if not self._points:
            raise ValueError("empty ring")
        idx = bisect.bisect_right(self._points, _hash(key))
        if idx == len(self._points):
            idx = 0
        return self._names[idx]
