"""Bounded exponential backoff with jitter, shared by every retry loop.

The reference's client and delegate retry loops all pace themselves
(yadcc-cxx.cc:191-248 retries infrastructure failures with a delay;
task_grant_keeper.cc polls on a demand window) — but several of this
reproduction's loops grew up as fixed-interval sleeps or, worse,
zero-delay spins (client/task_quota.py hot-spun on unexpected daemon
statuses until its 3600s timeout).  This module is the one definition
of "wait before retrying":

  * exponential growth with a hard ceiling (a dry scheduler must not be
    hammered, but a 30-minute build must not park for minutes either);
  * full jitter (uniform in (0, delay]): a thousand clients knocked
    over by the same scheduler restart must not re-arrive in lockstep;
  * server hints win: when the server said *when* to come back
    (retry-after, the overload ladder's REJECT verdict), that replaces
    the locally-computed delay — the server computed it from backlog it
    can see and we cannot.

Deterministic in tests: inject ``rng`` and ``sleep``.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional


class Backoff:
    """One retry loop's pacing state.  Not thread-safe: each loop owns
    its instance (two threads sharing one would double-advance the
    schedule)."""

    def __init__(
        self,
        initial_s: float = 0.05,
        max_s: float = 2.0,
        multiplier: float = 2.0,
        jitter: bool = True,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if initial_s <= 0 or max_s < initial_s or multiplier < 1.0:
            raise ValueError("backoff schedule must grow from a positive "
                             f"base: {initial_s=} {max_s=} {multiplier=}")
        self._initial = initial_s
        self._max = max_s
        self._multiplier = multiplier
        self._jitter = jitter
        self._rng = rng or random
        self._sleep = sleep
        self._next = initial_s
        self.retries = 0  # consecutive failures since the last reset()

    def reset(self) -> None:
        """Call on success: the next failure starts the schedule over."""
        self._next = self._initial
        self.retries = 0

    def next_delay(self, retry_after_s: Optional[float] = None) -> float:
        """The delay to wait before the next attempt (advances the
        schedule).  ``retry_after_s`` is a server hint: it replaces the
        computed delay, still clamped to the ceiling (a hostile or
        confused server must not park a client for an hour) and still
        jittered (every rejected client got the same hint)."""
        if retry_after_s is not None and retry_after_s > 0:
            base = min(retry_after_s, self._max)
        else:
            base = self._next
        self._next = min(self._next * self._multiplier, self._max)
        self.retries += 1
        if self._jitter:
            # Full jitter, floored at 10% of base so a pathological rng
            # draw can't turn backoff into a spin.
            return base * (0.1 + 0.9 * self._rng.random())
        return base

    def wait(self, retry_after_s: Optional[float] = None) -> float:
        """Sleep for next_delay(); returns the slept duration."""
        d = self.next_delay(retry_after_s)
        self._sleep(d)
        return d
