"""zlib-framed stand-in for the zstd helpers on hosts without the
`zstandard` wheel (see common/compress.py for the gating story).

Two frame kinds, distinguished by a 4-byte magic so the size-cap
check in compress.decompress keeps working:

  * one-shot  — ``YZF1`` + u64le declared size + zlib stream
    (frame_content_size reads the declared size, like a zstd frame
    header with content size set);
  * streaming — ``YZFS`` + zlib stream (declared size unknown, -1,
    like a zstd streaming frame).

Pure stdlib; never imported when the real wheel is present.
"""

from __future__ import annotations

import zlib

_ONE_SHOT_MAGIC = b"YZF1"
_STREAM_MAGIC = b"YZFS"


class Error(Exception):
    """Stands in for zstandard.ZstdError in except clauses."""


def compress(data: bytes, level: int = 3) -> bytes:
    return (_ONE_SHOT_MAGIC + len(data).to_bytes(8, "little")
            + zlib.compress(data, level))


def frame_content_size(data: bytes) -> int:
    """Declared decompressed size; -1 for streaming frames.  Raises on
    anything that isn't one of our frames — same contract as
    zstandard.frame_content_size on a malformed header."""
    if data[:4] == _ONE_SHOT_MAGIC and len(data) >= 12:
        return int.from_bytes(data[4:12], "little")
    if data[:4] == _STREAM_MAGIC:
        return -1
    raise Error("not a framed payload")


def decompress(data: bytes, max_output_size: int) -> bytes:
    declared = frame_content_size(data)        # raises on bad magic
    body = data[12:] if declared >= 0 else data[4:]
    obj = zlib.decompressobj()
    try:
        out = obj.decompress(body, max_output_size)
    except zlib.error as e:
        raise Error(str(e)) from None
    if obj.unconsumed_tail:
        raise Error(f"output exceeds cap {max_output_size}")
    if not obj.eof:
        raise Error("truncated stream")
    if declared >= 0 and len(out) != declared:
        raise Error("declared size mismatch")
    return out


class StreamCompressor:
    """compressobj() twin: .compress(bytes) / .flush(), magic-prefixed."""

    def __init__(self, level: int = 3):
        self._obj = zlib.compressobj(level)
        self._first = True

    def _prefix(self, out: bytes) -> bytes:
        if self._first:
            self._first = False
            return _STREAM_MAGIC + out
        return out

    def compress(self, data: bytes) -> bytes:
        return self._prefix(self._obj.compress(data))

    def flush(self) -> bytes:
        return self._prefix(self._obj.flush())


class AnyFrameDecompressor:
    """Streaming twin of :func:`decompress`: accepts EITHER frame kind
    (one-shot ``YZF1`` or streaming ``YZFS``) fed in arbitrary chunk
    sizes — the engine under compress.DecompressingDigestReader when the
    zstd wheel is absent.  Error semantics match the one-shot path:
    truncation and declared-size mismatch raise :class:`Error`; trailing
    bytes after the stream end are ignored (zlib routes them to
    ``unused_data``), exactly as ``decompress`` accepts them."""

    def __init__(self):
        self._obj = zlib.decompressobj()
        self._head = b""
        self._declared = None  # None until the magic is seen; -1 = stream
        self._out = 0

    def decompress(self, chunk) -> bytes:
        if self._declared is None:
            self._head += bytes(chunk)
            if len(self._head) < 4:
                return b""
            if self._head[:4] == _STREAM_MAGIC:
                self._declared = -1
                chunk, self._head = self._head[4:], b""
            elif self._head[:4] == _ONE_SHOT_MAGIC:
                if len(self._head) < 12:
                    return b""
                self._declared = int.from_bytes(self._head[4:12], "little")
                chunk, self._head = self._head[12:], b""
            else:
                raise Error("not a framed payload")
        try:
            out = self._obj.decompress(chunk)
        except zlib.error as e:
            raise Error(str(e)) from None
        self._out += len(out)
        return out

    def verify_eof(self) -> None:
        if self._declared is None or not self._obj.eof:
            raise Error("truncated stream")
        if self._declared >= 0 and self._out != self._declared:
            raise Error("declared size mismatch")


class StreamDecompressor:
    """decompressobj() twin for decompress_iter."""

    def __init__(self):
        self._obj = zlib.decompressobj()
        self._head = b""
        self._started = False

    def decompress(self, chunk: bytes) -> bytes:
        if not self._started:
            self._head += chunk
            if len(self._head) < 4:
                return b""
            if self._head[:4] != _STREAM_MAGIC:
                raise Error("not a streaming frame")
            chunk, self._head = self._head[4:], b""
            self._started = True
        try:
            return self._obj.decompress(chunk)
        except zlib.error as e:
            raise Error(str(e)) from None
