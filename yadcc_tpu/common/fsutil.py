"""Small filesystem helpers, parity with reference yadcc/common/{io,dir}.cc."""

from __future__ import annotations

import os
import shutil
from pathlib import Path
from typing import Dict, List


def read_all(path: str | os.PathLike) -> bytes:
    with open(path, "rb") as fp:
        return fp.read()


def write_all(path: str | os.PathLike, data: bytes) -> None:
    with open(path, "wb") as fp:
        fp.write(data)


def mkdirs(path: str | os.PathLike) -> None:
    Path(path).mkdir(parents=True, exist_ok=True)


def remove_tree(path: str | os.PathLike) -> None:
    shutil.rmtree(path, ignore_errors=True)


def enumerate_files(root: str | os.PathLike) -> List[str]:
    """Relative paths of all regular files under root."""
    rootp = Path(root)
    return sorted(
        str(p.relative_to(rootp))
        for p in rootp.rglob("*")
        if p.is_file()
    )


def read_tree(root: str | os.PathLike) -> Dict[str, bytes]:
    """relative path -> content for all files under root (used to collect
    a compilation workspace's outputs)."""
    rootp = Path(root)
    return {
        str(p.relative_to(rootp)): p.read_bytes()
        for p in rootp.rglob("*")
        if p.is_file()
    }


def file_mtime_size(path: str | os.PathLike) -> tuple[int, int]:
    st = os.stat(path)
    return int(st.st_mtime), st.st_size
