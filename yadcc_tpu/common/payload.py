"""Chunked payload buffers — the data plane's scatter/gather primitive.

The reference builds all of its transport on flare's NoncontiguousBuffer
(SNIPPETS/COMPONENTS §2.7): a task's bytes move from the preprocessor to
the servant and back as a *sequence of segments*, and the only place the
segments are ever flattened into one contiguous buffer is the socket
write.  This module is that analogue for the python data plane:

* ``Payload`` — an immutable sequence of ``bytes``/``memoryview``
  segments with ``len``, ``slice``, ``iter_segments`` and a single
  ``join`` reserved for the socket boundary.
* a process-wide **copy counter** — every materializing ``join`` (and
  every legacy-path concatenation routed through :func:`count_copy`)
  is recorded, so "how many times did this task's bytes get copied?"
  is a measured number (``tools/dataplane_bench``), asserted in tests
  rather than merely graphed.

Segments are never mutated and never defensively copied: callers hand
over ``bytes`` (already immutable) or views into buffers they keep
alive (a parsed RPC frame, an HTTP body).  A view pins its backing
buffer — for this data plane that is always the frame the segment was
parsed out of, which has the same lifetime anyway.
"""

from __future__ import annotations

import threading
from typing import Iterable, Iterator, List, Tuple, Union

Segment = Union[bytes, bytearray, memoryview]


class _CopyCounter:
    """Process-wide tally of full-buffer materializations.

    One "copy" is one event that re-materializes a buffer that already
    existed in memory (a ``join``, a parse that duplicates chunk bodies,
    a concatenation of already-built parts).  First-time allocations —
    compressor output, a file read — are not copies; both the legacy
    and the zero-copy path pay those identically.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._copies = 0  # guarded by: self._lock
        self._bytes = 0  # guarded by: self._lock

    def count(self, nbytes: int, events: int = 1) -> None:
        with self._lock:
            self._copies += events
            self._bytes += nbytes

    def snapshot(self) -> Tuple[int, int]:
        with self._lock:
            return self._copies, self._bytes


_COUNTER = _CopyCounter()


def count_copy(nbytes: int, events: int = 1) -> None:
    """Record `events` buffer copies totalling `nbytes` bytes.

    Exposed so the legacy-path models in ``tools/_dataplane_legacy`` and
    compat shims charge their concatenations to the same meter the
    Payload layer uses."""
    _COUNTER.count(nbytes, events)


def copy_stats() -> dict:
    copies, nbytes = _COUNTER.snapshot()
    return {"copies": copies, "bytes": nbytes}


class copy_counting:
    """Context manager capturing the copy-counter delta across a block::

        with copy_counting() as c:
            ...byte path under test...
        assert c.copies <= 4

    The counter is process-global; meaningful deltas come from
    single-threaded modeled paths (tests, the microbench) or from
    dividing a whole cluster run's delta by its task count.
    """

    copies: int = 0
    bytes: int = 0

    def __enter__(self) -> "copy_counting":
        self._c0, self._b0 = _COUNTER.snapshot()
        return self

    def __exit__(self, *exc) -> None:
        c1, b1 = _COUNTER.snapshot()
        self.copies = c1 - self._c0
        self.bytes = b1 - self._b0


class Payload:
    """Immutable sequence of byte segments; flattened only at ``join``."""

    __slots__ = ("_segments", "_length")

    def __init__(self, segments: Iterable[Segment] = ()):
        segs: List[Segment] = []
        total = 0
        for s in segments:
            if isinstance(s, Payload):
                # Flatten nested payloads: segments stay shared, no copy.
                segs.extend(s._segments)
                total += s._length
                continue
            if isinstance(s, memoryview) and (
                    not s.contiguous or s.format != "B"):
                # Join/socket writes need plain contiguous byte buffers;
                # exotic views (a reversed slice, a typed array) are
                # normalized here, at the edge.
                s = s.tobytes()
            n = len(s)
            if n == 0:
                continue
            segs.append(s)
            total += n
        self._segments: Tuple[Segment, ...] = tuple(segs)
        self._length = total

    @classmethod
    def of(cls, *parts: Union[Segment, "Payload"]) -> "Payload":
        return cls(parts)

    @classmethod
    def from_bytes(cls, data: Segment) -> "Payload":
        return cls((data,))

    def __len__(self) -> int:
        return self._length

    def __bool__(self) -> bool:
        return self._length > 0

    def iter_segments(self) -> Iterator[Segment]:
        return iter(self._segments)

    @property
    def num_segments(self) -> int:
        return len(self._segments)

    def slice(self, start: int, stop: int) -> "Payload":
        """Payload view of [start, stop) — segment views, no copying."""
        start = max(0, min(start, self._length))
        stop = max(start, min(stop, self._length))
        out: List[Segment] = []
        off = 0
        for seg in self._segments:
            n = len(seg)
            if off + n <= start:
                off += n
                continue
            if off >= stop:
                break
            lo = max(0, start - off)
            hi = min(n, stop - off)
            out.append(memoryview(seg)[lo:hi] if (lo, hi) != (0, n)
                       else seg)
            off += n
        return Payload(out)

    def join(self) -> bytes:
        """Materialize into one contiguous ``bytes`` — THE copy.

        Reserved for the socket boundary (and compat shims).  A payload
        that is already a single ``bytes`` segment is returned as-is
        and counts nothing."""
        if not self._segments:
            return b""
        if len(self._segments) == 1 and isinstance(self._segments[0], bytes):
            return self._segments[0]
        _COUNTER.count(self._length)
        return b"".join(self._segments)

    def update_into(self, hasher) -> None:
        """Feed every segment to `hasher.update` — the incremental-digest
        partner of ``hashing.new_digest()`` (no concatenation)."""
        for seg in self._segments:
            hasher.update(seg)

    def __repr__(self) -> str:
        return (f"Payload({self._length} bytes, "
                f"{len(self._segments)} segments)")


def as_payload(data: Union[Segment, Payload]) -> Payload:
    return data if isinstance(data, Payload) else Payload.from_bytes(data)
