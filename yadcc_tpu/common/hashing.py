"""Content digests.

The reference hashes everything with BLAKE3 (~3 GB/s, chosen over MD5/SHA1
for CPU budget — yadcc/doc/client/cxx.md:61-68).  CPython ships no BLAKE3,
so this framework standardizes on BLAKE2b-256 from hashlib, which is in
the same performance class and, like BLAKE3, is keyed/salted-capable.
Digest strings are lowercase hex and opaque to every protocol.
"""

from __future__ import annotations

import hashlib
import os
from typing import BinaryIO

_DIGEST_SIZE = 32


def new_digest():
    return hashlib.blake2b(digest_size=_DIGEST_SIZE)


def digest_bytes(*parts: bytes) -> str:
    """Digest of the concatenation of `parts`, hex-encoded."""
    h = new_digest()
    for p in parts:
        h.update(p)
    return h.hexdigest()


def digest_keyed(domain: str, *parts: bytes) -> str:  # ytpu: sanitizes(key-domain)
    """Domain-separated digest: each part is length-prefixed so component
    boundaries can't be confused (unlike plain concatenation)."""
    h = hashlib.blake2b(digest_size=_DIGEST_SIZE, person=domain.encode()[:16])
    for p in parts:
        h.update(len(p).to_bytes(8, "little"))
        h.update(p)
    return h.hexdigest()


def digest_stream(fp: BinaryIO, chunk_size: int = 1 << 20) -> str:
    h = new_digest()
    while True:
        chunk = fp.read(chunk_size)
        if not chunk:
            break
        h.update(chunk)
    return h.hexdigest()


def digest_file(path: str | os.PathLike) -> str:
    with open(path, "rb") as fp:
        return digest_stream(fp)


class DigestingWriter:
    """Output-stream sink that digests everything written through it.

    Mirrors the client's Blake3OutputStream (reference
    yadcc/client/common/output_stream.{h,cc}) so preprocessing can stream
    into compression and hashing in a single pass.
    """

    def __init__(self):
        self._h = new_digest()
        self.bytes_written = 0

    def write(self, data: bytes) -> int:
        self._h.update(data)
        self.bytes_written += len(data)
        return len(data)

    def hexdigest(self) -> str:
        return self._h.hexdigest()
