"""Sharded on-disk KV store for the cache server's L2.

Parity with reference yadcc/common/disk_cache.h:42-110 — deliberately NOT
an LSM/embedded DB (yadcc/doc/cache.md:29-35): entries are ~1MB blobs,
one file each, so a plain directory tree with size caps is both simpler
and faster to operate.

Layout: each configured shard is a directory with its own byte-size cap.
A key picks its shard via a weighted consistent-hash ring (stable under
shard add/remove), then lands in a 2-level / 16-way fan-out subdirectory
derived from the key digest's leading nibbles.  Values are written via a
temp file + rename so readers never observe partial entries.  An
LRU-flavored purge evicts oldest-accessed files when a shard exceeds its
cap.  On startup, shards are rescanned to rebuild size accounting, and
entries whose key no longer hashes to the shard they sit in (after a
topology change) are handled per the misplaced-entry policy:
delete / move / ignore (reference --disk_engine_action_on_misplaced_cache_entry,
yadcc/doc/cache.md:65-69).

All internal bookkeeping is keyed by the key's hex digest (which is also
the on-disk file name), so entries discovered by the startup scan — for
which the original key string is unknown — behave identically to entries
written through put().  Timestamps are epoch seconds (time.time) so
scanned file mtimes and fresh writes share one clock domain.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from .consistent_hash import ConsistentHash
from .hashing import digest_bytes


@dataclass
class ShardSpec:
    path: str
    capacity_bytes: int
    weight: int = 1


@dataclass
class _Entry:
    size: int
    last_used: float  # epoch seconds


_tmp_counter = itertools.count()


class DiskCache:
    ON_MISPLACED_DELETE = "delete"
    ON_MISPLACED_MOVE = "move"
    ON_MISPLACED_IGNORE = "ignore"

    def __init__(
        self,
        shards: Sequence[ShardSpec],
        on_misplaced: str = ON_MISPLACED_MOVE,
        sweep_on_start: bool = True,
    ):
        if not shards:
            raise ValueError("at least one shard required")
        if on_misplaced not in (self.ON_MISPLACED_DELETE,
                                self.ON_MISPLACED_MOVE,
                                self.ON_MISPLACED_IGNORE):
            raise ValueError(f"unknown misplaced-entry policy {on_misplaced!r}")
        self._shards: Dict[str, ShardSpec] = {s.path: s for s in shards}
        self._ring = ConsistentHash([(s.path, s.weight) for s in shards])
        self._lock = threading.Lock()
        # Per-shard: digest -> entry bookkeeping, plus running byte total.
        self._entries: Dict[str, Dict[str, _Entry]] = {
            s.path: {} for s in shards
        }
        self._sizes: Dict[str, int] = {s.path: 0 for s in shards}
        for s in shards:
            Path(s.path).mkdir(parents=True, exist_ok=True)
        if sweep_on_start:
            self._startup_scan(on_misplaced)

    # -- key placement -----------------------------------------------------

    @staticmethod
    def _key_digest(key: str) -> str:
        return digest_bytes(key.encode())

    @staticmethod
    def _digest_path(shard: str, digest: str) -> Path:
        return Path(shard) / digest[0] / digest[1] / digest

    def _place(self, key: str) -> Tuple[str, str]:
        """key -> (shard, digest)."""
        digest = self._key_digest(key)
        return self._ring.pick(digest), digest

    # -- public API --------------------------------------------------------

    def try_get(self, key: str) -> Optional[bytes]:
        shard, digest = self._place(key)
        try:
            data = self._digest_path(shard, digest).read_bytes()
        except FileNotFoundError:
            return None
        with self._lock:
            e = self._entries[shard].get(digest)
            if e is not None:
                e.last_used = time.time()
        return data

    def put(self, key: str, value: bytes) -> None:
        shard, digest = self._place(key)
        path = self._digest_path(shard, digest)
        path.parent.mkdir(parents=True, exist_ok=True)
        # pid + thread id + counter: concurrent writers of the same key in
        # one process must not share a temp file.
        tmp = path.with_name(
            f"{path.name}.tmp{os.getpid()}_{threading.get_native_id()}"
            f"_{next(_tmp_counter)}"
        )
        tmp.write_bytes(value)
        os.replace(tmp, path)
        with self._lock:
            old = self._entries[shard].pop(digest, None)
            if old is not None:
                self._sizes[shard] -= old.size
            self._entries[shard][digest] = _Entry(len(value), time.time())
            self._sizes[shard] += len(value)
            self._purge_locked(shard)

    def remove(self, key: str) -> bool:
        shard, digest = self._place(key)
        try:
            self._digest_path(shard, digest).unlink()
        except FileNotFoundError:
            return False
        with self._lock:
            old = self._entries[shard].pop(digest, None)
            if old is not None:
                self._sizes[shard] -= old.size
        return True

    def contains(self, key: str) -> bool:
        shard, digest = self._place(key)
        with self._lock:
            if digest in self._entries[shard]:
                return True
        return self._digest_path(shard, digest).exists()

    def digests(self) -> List[str]:
        """Digests of all stored entries (key strings are not recoverable;
        callers that need keys must track them separately)."""
        with self._lock:
            out: List[str] = []
            for entries in self._entries.values():
                out.extend(entries.keys())
            return out

    def entry_count(self) -> int:
        with self._lock:
            return sum(len(e) for e in self._entries.values())

    def total_bytes(self) -> int:
        with self._lock:
            return sum(self._sizes.values())

    def stats(self) -> Dict[str, Tuple[int, int]]:
        """shard -> (entries, bytes)."""
        with self._lock:
            return {
                s: (len(self._entries[s]), self._sizes[s]) for s in self._shards
            }

    def purge(self) -> None:
        """Periodic maintenance: trim every shard back to capacity.
        The write path already purges the shard it touches; this pass
        covers shards whose capacity was reduced (restart with a
        smaller --cache-dirs quota) or that were filled by the startup
        scan rather than writes."""
        with self._lock:
            for shard in self._shards:
                self._purge_locked(shard)

    # -- internals ---------------------------------------------------------

    def _purge_locked(self, shard: str) -> None:
        cap = self._shards[shard].capacity_bytes
        if self._sizes[shard] <= cap:
            return
        victims = sorted(
            self._entries[shard].items(), key=lambda kv: kv[1].last_used
        )
        for digest, e in victims:
            if self._sizes[shard] <= cap:
                break
            try:
                self._digest_path(shard, digest).unlink(missing_ok=True)
            except OSError:
                pass
            del self._entries[shard][digest]
            self._sizes[shard] -= e.size

    def _register_scanned(self, shard: str, digest: str, size: int,
                          mtime: float) -> None:
        # A moved entry may be seen twice (once when moved in, once when
        # its new shard is scanned); register exactly once.
        if digest in self._entries[shard]:
            return
        self._entries[shard][digest] = _Entry(size, mtime)
        self._sizes[shard] += size

    def _startup_scan(self, on_misplaced: str) -> None:
        """Rebuild bookkeeping from disk; reconcile misplaced entries.

        File names are key digests, so a file's *correct* shard is
        computable from its name alone.
        """
        for shard in self._shards:
            root = Path(shard)
            for f in root.glob("*/*/*"):
                if not f.is_file():
                    continue
                if ".tmp" in f.name:  # leftover from a crashed writer
                    f.unlink(missing_ok=True)
                    continue
                digest = f.name
                correct = self._ring.pick(digest)
                try:
                    st = f.stat()
                except OSError:
                    continue
                if correct != shard:
                    if on_misplaced == self.ON_MISPLACED_DELETE:
                        f.unlink(missing_ok=True)
                        continue
                    if on_misplaced == self.ON_MISPLACED_MOVE:
                        dst = self._digest_path(correct, digest)
                        if digest in self._entries[correct] or dst.exists():
                            # The correct shard already holds this entry
                            # (same key, same digest -> same value modulo
                            # write time); drop the misplaced duplicate
                            # instead of clobbering registered accounting.
                            f.unlink(missing_ok=True)
                            continue
                        dst.parent.mkdir(parents=True, exist_ok=True)
                        try:
                            os.replace(f, dst)
                        except OSError:
                            continue
                        self._register_scanned(correct, digest, st.st_size,
                                               st.st_mtime)
                        continue
                    # ignore: account for it where it sits.
                self._register_scanned(shard, digest, st.st_size, st.st_mtime)
