"""Timestamp-macro cacheability rules, shared by client and servant.

A TU that expands __TIME__/__DATE__/__TIMESTAMP__ produces a different
object every build; caching it would freeze the clock for the whole
fleet (reference remote_task/cxx_compilation_task.cc:46-76).  The
exception: a command-line -D override of the macro (the standard
reproducible-build workaround) makes the expansion deterministic again.

Both sides apply the SAME rule from this module — the client for its
YTPU_WARN_ON_NONCACHEABLE diagnostic, the servant for the authoritative
cache-fill decision — so the warning can never disagree with what the
cache actually does.
"""

from __future__ import annotations

import shlex
from typing import Iterable, Set

TIMESTAMP_MACROS = (b"__TIME__", b"__DATE__", b"__TIMESTAMP__")


def overridden_macros(invocation_arguments: str) -> Set[bytes]:
    """Macro names neutralized by -D on the command line."""
    out: Set[bytes] = set()
    for arg in shlex.split(invocation_arguments):
        if arg.startswith("-D"):
            out.add(arg[2:].split("=", 1)[0].encode())
    return out


def blocking_macros(found: Iterable[bytes],
                    invocation_arguments: str) -> Set[bytes]:
    """Which of the macros `found` in the source actually block caching
    (i.e. are not -D-overridden)."""
    return set(found) - overridden_macros(invocation_arguments)


def scan_source_cacheability(source: bytes,
                             invocation_arguments: str) -> bool:
    """False if the preprocessed source expands timestamp macros the
    command line doesn't override."""
    found = [m for m in TIMESTAMP_MACROS if m in source]
    return not blocking_macros(found, invocation_arguments)
