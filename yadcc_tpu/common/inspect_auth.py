"""HTTP Basic-auth gate for /inspect/* endpoints, parity with reference
yadcc/common/inspect_auth.h:23-31 (--inspect_credential)."""

from __future__ import annotations

import base64
import hmac
from typing import Optional


class InspectAuth:
    def __init__(self, credential: str = ""):
        """credential: "user:password"; empty disables auth."""
        self._credential = credential

    def check(self, authorization_header: Optional[str]) -> bool:
        if not self._credential:
            return True
        if not authorization_header:
            return False
        parts = authorization_header.split(None, 1)
        if len(parts) != 2 or parts[0].lower() != "basic":
            return False
        try:
            decoded = base64.b64decode(parts[1]).decode()
        except Exception:
            return False
        return hmac.compare_digest(decoded, self._credential)
