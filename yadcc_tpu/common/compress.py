"""zstd helpers (one-shot + streaming), parity with the reference's
flare compression and the client's zstd output stream
(yadcc/client/common/compress.{h,cc}, output_stream.{h,cc}).

When the `zstandard` wheel is absent (minimal containers), a stdlib
zlib stand-in keeps the same API: framed one-shot payloads carry a
declared-size header so the pre-allocation cap check still works, and
streaming frames decompress under the same output cap.  The two
formats do not interoperate — every component in a zstd-less process
speaks the fallback, which is the only deployment such a process can
be part of anyway (the wire peer would need the same build)."""

from __future__ import annotations

import os
from typing import Iterable, Optional, Tuple

try:
    import zstandard
except ImportError:  # gated: minimal containers ship no zstd wheel
    zstandard = None
    from . import _zlib_frames as _fallback

from .hashing import new_digest

# Reference tunes for throughput, not ratio: zstd eats ~15% of client CPU
# at the default level (yadcc/doc/rationale.md:94).
_LEVEL = 3

# YTPU_COMPRESS_LEVEL bounds; values outside fall back to the default
# rather than erroring (a typo'd env var must not break every compile).
# zstd's ultra levels (20+) need window-log opt-ins and are never a
# throughput tune; the zlib stand-in caps at 9.
_MAX_LEVEL = 19 if zstandard is not None else 9

# The error type callers may catch regardless of which backend is
# compiled in (zstandard.ZstdError when the wheel is present).
CompressionError = (zstandard.ZstdError if zstandard is not None
                    else _fallback.Error)

# zstandard (de)compressor objects are not safe for concurrent use from
# multiple threads, and the daemons serve RPCs on thread pools — keep one
# per thread.
import threading

_tls = threading.local()


def current_level() -> int:
    """Active compression level: YTPU_COMPRESS_LEVEL when it parses to a
    level the backend supports, else the reference's throughput tune
    (3).  Read per call so tests (and long-lived daemons told to
    re-exec) see env changes; the parse costs nanoseconds against any
    payload worth compressing."""
    raw = os.environ.get("YTPU_COMPRESS_LEVEL")
    if not raw:
        return _LEVEL
    try:
        v = int(raw)
    except ValueError:
        return _LEVEL
    return v if 1 <= v <= _MAX_LEVEL else _LEVEL


def _ctx() -> tuple:
    level = current_level()
    trio = getattr(_tls, "trio", None)
    if trio is None or trio[0] != level:
        trio = (
            level,
            zstandard.ZstdCompressor(level=level),
            zstandard.ZstdDecompressor(),
        )
        _tls.trio = trio
    return trio[1:]


def compress(data: bytes) -> bytes:
    if zstandard is None:
        return _fallback.compress(data, current_level())
    return _ctx()[0].compress(data)


# Decompressed payloads beyond this are treated as corruption.  Wire
# packets cap at 1GB compressed (reference daemon/entry.cc, sized for
# Java jars); 2GB decompressed leaves headroom for zstd's typical ratios
# on preprocessed C++ without letting a frame demand absurd allocations.
_MAX_DECOMPRESSED = 1 << 31


def decompress(data: bytes, max_output_size: int = _MAX_DECOMPRESSED) -> bytes:  # ytpu: sanitizes(size-cap)
    # max_output_size only binds STREAMING frames (no content size in
    # the header) — python-zstandard ignores it when the frame declares
    # a size, so a hostile 16KB frame declaring terabytes would attempt
    # the full allocation (fuzz-found, tests/test_fuzz_parsers.py).
    # Check the declared size ourselves before touching the allocator
    # (-1 = streaming/unknown; raises on malformed headers).
    if zstandard is None:
        return _fallback.decompress(data, max_output_size)
    declared = zstandard.frame_content_size(data)
    if declared > max_output_size:
        raise zstandard.ZstdError(
            f"declared content size {declared} exceeds cap")
    return _ctx()[1].decompress(data, max_output_size=max_output_size)


def try_decompress(data: bytes) -> Optional[bytes]:  # ytpu: sanitizes(size-cap)
    try:
        return decompress(data)
    except (CompressionError, MemoryError, ValueError):
        # Corruption — including allocation-level failures — must read
        # as a miss, never take down the serving thread.
        return None


class CompressingWriter:
    """Streaming zstd sink chaining into a downstream writer; composable
    with hashing.DigestingWriter to form the client's single-pass
    preprocess -> (digest, zstd) tee."""

    def __init__(self, sink):
        self._sink = sink
        level = current_level()
        self._obj = (_fallback.StreamCompressor(level)
                     if zstandard is None
                     else zstandard.ZstdCompressor(level=level)
                     .compressobj())
        self._closed = False

    def write(self, data: bytes) -> int:
        out = self._obj.compress(data)
        if out:
            self._sink.write(out)
        return len(data)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            out = self._obj.flush()
            if out:
                self._sink.write(out)


class TeeWriter:
    """Fan a write out to several sinks (ForwardingOutputStream parity)."""

    def __init__(self, *sinks):
        self._sinks = sinks

    def write(self, data: bytes) -> int:
        for s in self._sinks:
            s.write(data)
        return len(data)


def decompress_iter(chunks: Iterable[bytes]) -> bytes:
    obj = (_fallback.StreamDecompressor() if zstandard is None
           else _ctx()[1].decompressobj())
    return b"".join(obj.decompress(c) for c in chunks)


class DecompressingDigestReader:
    """Fused streaming decompress ⊕ BLAKE2b-256 — one pass over the
    bytes instead of decompress-everything-then-rescan-to-digest.

    The servant-side mirror of the client's compress⊕digest tee
    (CompressingWriter + hashing.DigestingWriter): feed compressed
    chunks with :meth:`feed`, each decompressed piece is digested as it
    appears; :meth:`finish` verifies stream completeness.  The output
    cap binds on *produced* bytes, so a hostile frame aborts mid-stream
    instead of after a giant allocation.  All failures raise
    :data:`CompressionError`; callers discard any partial output.
    """

    def __init__(self, max_output_size: int = _MAX_DECOMPRESSED):
        self._h = new_digest()
        self._cap = max_output_size
        self.bytes_out = 0
        self._obj = (_fallback.AnyFrameDecompressor() if zstandard is None
                     else zstandard.ZstdDecompressor().decompressobj())

    def feed(self, chunk) -> bytes:  # ytpu: sanitizes(size-cap)
        out = self._obj.decompress(chunk)
        self.bytes_out += len(out)
        if self.bytes_out > self._cap:
            raise CompressionError(f"output exceeds cap {self._cap}")
        if out:
            self._h.update(out)
        return out

    def finish(self) -> None:
        if zstandard is None:
            self._obj.verify_eof()
        elif not getattr(self._obj, "eof", True):
            raise CompressionError("truncated stream")

    def hexdigest(self) -> str:
        return self._h.hexdigest()


def decompress_and_digest(
    data,
    max_output_size: int = _MAX_DECOMPRESSED,
    chunk_size: int = 1 << 20,
) -> Tuple[bytes, str]:  # ytpu: sanitizes(size-cap, digest)
    """Single-pass (decompressed bytes, hex digest) of a complete frame.

    Error contract matches :func:`decompress` — corruption, truncation,
    a hostile declared size, or cap overflow raise
    :data:`CompressionError`; no partial output escapes."""
    mv = memoryview(data)
    # Same fail-fast declared-size check as decompress(): a tiny frame
    # declaring terabytes is refused before any work.
    declared = (_fallback.frame_content_size(mv) if zstandard is None
                else zstandard.frame_content_size(data))
    if declared > max_output_size:
        raise CompressionError(
            f"declared content size {declared} exceeds cap")
    reader = DecompressingDigestReader(max_output_size)
    pieces = []
    for off in range(0, len(mv), chunk_size):
        out = reader.feed(mv[off:off + chunk_size])
        if out:
            pieces.append(out)
    reader.finish()
    return b"".join(pieces), reader.hexdigest()
