"""zstd helpers (one-shot + streaming), parity with the reference's
flare compression and the client's zstd output stream
(yadcc/client/common/compress.{h,cc}, output_stream.{h,cc}).

When the `zstandard` wheel is absent (minimal containers), a stdlib
zlib stand-in keeps the same API: framed one-shot payloads carry a
declared-size header so the pre-allocation cap check still works, and
streaming frames decompress under the same output cap.  The two
formats do not interoperate — every component in a zstd-less process
speaks the fallback, which is the only deployment such a process can
be part of anyway (the wire peer would need the same build)."""

from __future__ import annotations

from typing import Iterable, Optional

try:
    import zstandard
except ImportError:  # gated: minimal containers ship no zstd wheel
    zstandard = None
    from . import _zlib_frames as _fallback

# Reference tunes for throughput, not ratio: zstd eats ~15% of client CPU
# at the default level (yadcc/doc/rationale.md:94).
_LEVEL = 3

# The error type callers may catch regardless of which backend is
# compiled in (zstandard.ZstdError when the wheel is present).
CompressionError = (zstandard.ZstdError if zstandard is not None
                    else _fallback.Error)

# zstandard (de)compressor objects are not safe for concurrent use from
# multiple threads, and the daemons serve RPCs on thread pools — keep one
# per thread.
import threading

_tls = threading.local()


def _ctx() -> tuple:
    pair = getattr(_tls, "pair", None)
    if pair is None:
        pair = (
            zstandard.ZstdCompressor(level=_LEVEL),
            zstandard.ZstdDecompressor(),
        )
        _tls.pair = pair
    return pair


def compress(data: bytes) -> bytes:
    if zstandard is None:
        return _fallback.compress(data, _LEVEL)
    return _ctx()[0].compress(data)


# Decompressed payloads beyond this are treated as corruption.  Wire
# packets cap at 1GB compressed (reference daemon/entry.cc, sized for
# Java jars); 2GB decompressed leaves headroom for zstd's typical ratios
# on preprocessed C++ without letting a frame demand absurd allocations.
_MAX_DECOMPRESSED = 1 << 31


def decompress(data: bytes, max_output_size: int = _MAX_DECOMPRESSED) -> bytes:
    # max_output_size only binds STREAMING frames (no content size in
    # the header) — python-zstandard ignores it when the frame declares
    # a size, so a hostile 16KB frame declaring terabytes would attempt
    # the full allocation (fuzz-found, tests/test_fuzz_parsers.py).
    # Check the declared size ourselves before touching the allocator
    # (-1 = streaming/unknown; raises on malformed headers).
    if zstandard is None:
        return _fallback.decompress(data, max_output_size)
    declared = zstandard.frame_content_size(data)
    if declared > max_output_size:
        raise zstandard.ZstdError(
            f"declared content size {declared} exceeds cap")
    return _ctx()[1].decompress(data, max_output_size=max_output_size)


def try_decompress(data: bytes) -> Optional[bytes]:
    try:
        return decompress(data)
    except (CompressionError, MemoryError, ValueError):
        # Corruption — including allocation-level failures — must read
        # as a miss, never take down the serving thread.
        return None


class CompressingWriter:
    """Streaming zstd sink chaining into a downstream writer; composable
    with hashing.DigestingWriter to form the client's single-pass
    preprocess -> (digest, zstd) tee."""

    def __init__(self, sink):
        self._sink = sink
        self._obj = (_fallback.StreamCompressor(_LEVEL)
                     if zstandard is None
                     else zstandard.ZstdCompressor(level=_LEVEL)
                     .compressobj())
        self._closed = False

    def write(self, data: bytes) -> int:
        out = self._obj.compress(data)
        if out:
            self._sink.write(out)
        return len(data)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            out = self._obj.flush()
            if out:
                self._sink.write(out)


class TeeWriter:
    """Fan a write out to several sinks (ForwardingOutputStream parity)."""

    def __init__(self, *sinks):
        self._sinks = sinks

    def write(self, data: bytes) -> int:
        for s in self._sinks:
            s.write(data)
        return len(data)


def decompress_iter(chunks: Iterable[bytes]) -> bytes:
    obj = (_fallback.StreamDecompressor() if zstandard is None
           else _ctx()[1].decompressobj())
    return b"".join(obj.decompress(c) for c in chunks)
