"""Validated bounds for untrusted quantities at the trust boundary.

Every helper here is a declared sanitizer for the static taint pass
(doc/static_analysis.md): routing an intake value through one of these
is what lets ``ytpu-analyze`` prove the size-cap discipline instead of
trusting that each handler remembered it.

The caps mirror the reference's wire limits: packets cap at 1GB
compressed (reference daemon/entry.cc — sized for Java jars), and the
decompression side enforces its own 2GB produced-bytes cap
(common/compress.py).
"""

from __future__ import annotations

from typing import Optional, Union

# One HTTP request / RPC attachment may not exceed the wire packet cap.
MAX_WIRE_BODY = 1 << 30

# A client-supplied long-poll / quota wait may park a serving thread at
# most this long; clients re-poll (they already do — both wait routes
# are long-poll loops with their own deadline handling).
MAX_WAIT_S = 60.0


class BodyTooLarge(ValueError):
    """Request body exceeds the wire cap; HTTP layer answers 413."""


def checked_content_length(raw: Optional[Union[str, int]],
                           cap: int = MAX_WIRE_BODY) -> int:  # ytpu: sanitizes(size-cap)
    """Parse and bound a Content-Length header BEFORE buffering the
    body: a hostile local client claiming terabytes must be refused at
    the header, not at the allocator."""
    try:
        n = int(raw or 0)
    except (TypeError, ValueError):
        raise BodyTooLarge(f"unparseable content length {raw!r}")
    if n < 0 or n > cap:
        raise BodyTooLarge(f"content length {n} exceeds cap {cap}")
    return n


def checked_attachment(data, cap: int = MAX_WIRE_BODY):  # ytpu: sanitizes(size-cap)
    """Bound an already-buffered attachment (compressed source /
    StableHLO) to the wire cap; returns it unchanged.  The factory-side
    twin of the servant's decompression cap — the delegate must not
    queue (and re-send N times on retry) a payload no servant will
    accept."""
    if len(data) > cap:
        raise ValueError(f"attachment of {len(data)} bytes exceeds "
                         f"wire cap {cap}")
    return data


def clamp_wait_s(milliseconds: Union[int, float],
                 max_s: float = MAX_WAIT_S) -> float:  # ytpu: sanitizes(size-cap)
    """Client-supplied wait-milliseconds -> bounded seconds.  Negative
    and NaN-ish inputs clamp to zero."""
    try:
        s = float(milliseconds) / 1000.0
    except (TypeError, ValueError):
        return 0.0
    if not (s > 0):  # catches NaN too
        return 0.0
    return min(s, max_s)
