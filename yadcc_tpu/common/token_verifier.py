"""Set-membership token auth, parity with reference
yadcc/common/token_verifier.h:32-59.  Tokens are opaque strings; an empty
verifier accepts everything (matching the reference's permissive default
when no tokens are configured)."""

from __future__ import annotations

import secrets
from typing import Iterable, Set


class TokenVerifier:
    def __init__(self, tokens: Iterable[str] = ()):
        self._tokens: Set[str] = {t for t in tokens if t}

    def verify(self, token: str) -> bool:
        if not self._tokens:
            return True
        return token in self._tokens

    @property
    def empty(self) -> bool:
        return not self._tokens


def make_token_verifier_from_flag(flag_value: str) -> TokenVerifier:
    """Comma-separated token list, as in --acceptable_user_tokens."""
    return TokenVerifier(t.strip() for t in flag_value.split(",") if t.strip())


def generate_token(nbytes: int = 16) -> str:
    """Random token, used for the scheduler's hourly-rotating
    serving-daemon token (reference scheduler_service_impl.cc:46-51)."""
    return secrets.token_hex(nbytes)
