"""Vectorized XXH64 over key batches (numpy u64, lane-parallel).

The Bloom control plane fingerprints MILLIONS of cache keys
(common/bloom.py); the per-key C-extension call costs ~400-870ns —
up to 1s per 1M-key batch, dwarfing the probe itself (round-2
artifacts/bloom_bench.json).  This module computes the identical
XXH64 digest lane-parallel over a [N, L] byte matrix (~30 u64 vector
ops per 32-byte stripe amortized across the whole batch), with the
batch→matrix pack itself done in one C-level numpy conversion and the
vector math running in cache-sized chunks through preallocated
scratch buffers.

Bit-identical to the reference algorithm (public XXH64 spec, the same
one the `xxhash` wheel wraps); `tests/test_bloom_fast.py` cross-checks
against the C implementation over every tail-length class.  numpy's
u64 arithmetic wraps modulo 2^64, which is exactly the semantics the
algorithm needs.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

_P1 = np.uint64(0x9E3779B185EBCA87)
_P2 = np.uint64(0xC2B2AE3D27D4EB4F)
_P3 = np.uint64(0x165667B19E3779F9)
_P4 = np.uint64(0x85EBCA77C2B2AE63)
_P5 = np.uint64(0x27D4EB2F165667C5)
_M64 = np.uint64(0xFFFFFFFFFFFFFFFF)


# Rows per digest chunk: the lane math runs ~40-60 full-vector passes,
# so the working set (h/t/s/l u64 buffers + the byte rows) must stay
# cache-resident or every pass round-trips DRAM.  16k rows keeps it
# ~1MB — L2-sized; the sweep on the 1-core harness: 16k = 0.026s/1M
# 23-byte keys vs 0.037s at 128k and 0.042s at 256k (and unchunked was
# 0.12s with the >=32 stripe path thrashing at 9x that).
_CHUNK_ROWS = 16_384


def xxh64_batch(data: np.ndarray, seed: int,
                length: int | None = None) -> np.ndarray:
    """XXH64 of every row of a [N, W] uint8 matrix (one key per row,
    each key being the row's first `length` bytes — all of them when
    `length` is None), with the given seed.  Returns uint64[N].

    When W is exactly `length` rounded up to 8 and the bytes past
    `length` are zero (the pack_key_matrix layout), rows digest
    straight out of the caller's matrix — no pad copy.  Large batches
    run in cache-sized row chunks, and every vector op writes into one
    of three preallocated scratch buffers: a fresh 1MB numpy temporary
    per op is an mmap/page-fault round-trip at glibc's allocation
    threshold, and killing those measured 3x on the tail path (1-core
    harness)."""
    if data.ndim != 2 or data.dtype != np.uint8:
        raise ValueError("data must be a [N, L] uint8 matrix")
    n, width = data.shape
    if length is None:
        length = width
    elif length > width:
        raise ValueError(f"length {length} exceeds row width {width}")
    out = np.empty(n, np.uint64)
    c = min(n, _CHUNK_ROWS)
    scratch = tuple(np.empty(c, np.uint64) for _ in range(3))
    for i in range(0, n, _CHUNK_ROWS):
        _xxh64_batch_chunk(data[i:i + _CHUNK_ROWS], seed, length,
                           scratch, out[i:i + _CHUNK_ROWS])
    return out


_U64 = np.uint64


def _rotl_ip(x: np.ndarray, r: int, tmp: np.ndarray) -> None:
    """x <- rotl64(x, r), elementwise in place (tmp: same-shape u64)."""
    np.left_shift(x, _U64(r), out=tmp)
    np.right_shift(x, _U64(64 - r), out=x)
    np.bitwise_or(x, tmp, out=x)


def _rotl_into(x: np.ndarray, r: int, res: np.ndarray,
               tmp: np.ndarray) -> None:
    """res <- rotl64(x, r) without touching x (res, tmp distinct)."""
    np.left_shift(x, _U64(r), out=tmp)
    np.right_shift(x, _U64(64 - r), out=res)
    np.bitwise_or(res, tmp, out=res)


def _round_ip(acc: np.ndarray, lane: np.ndarray, t: np.ndarray) -> None:
    """acc <- rotl(acc + lane * P2, 31) * P1 (the XXH64 round)."""
    np.multiply(lane, _P2, out=t)
    np.add(acc, t, out=acc)
    _rotl_ip(acc, 31, t)
    np.multiply(acc, _P1, out=acc)


def _merge_round_ip(h: np.ndarray, acc: np.ndarray, t: np.ndarray,
                    s: np.ndarray) -> None:
    """h <- (h ^ round(0, acc)) * P1 + P4, preserving acc."""
    np.multiply(acc, _P2, out=t)
    _rotl_ip(t, 31, s)
    np.multiply(t, _P1, out=t)
    np.bitwise_xor(h, t, out=h)
    np.multiply(h, _P1, out=h)
    np.add(h, _P4, out=h)


def _xxh64_batch_chunk(data: np.ndarray, seed: int, length: int,
                       scratch: tuple, h: np.ndarray) -> None:
    """Digest one row chunk into `h` (uint64[n] output buffer)."""
    n = data.shape[0]
    t, s, l = (a[:n] for a in scratch)
    seed_i = int(seed) & int(_M64)

    # All u64 reads land on 8-byte offsets (stripes consume 32, the
    # tail loop 8 at a time) and the sole u32 read on a 4-byte offset,
    # so the matrix must be an 8-byte-multiple width to reinterpret:
    # each read is then one contiguous little-endian column view.
    # pack_key_matrix emits exactly that layout (zero tail bytes), so
    # the pad copy below only runs for hand-built matrices.
    aligned = length + (-length) % 8
    if data.shape[1] == aligned:
        padded = np.ascontiguousarray(data)
    else:
        padded = np.ascontiguousarray(
            np.pad(data[:, :length], ((0, 0), (0, aligned - length))))
    w64 = padded.view("<u8")
    w32 = padded.view("<u4")

    def lane64(off: int) -> np.ndarray:
        l[:] = w64[:, off // 8]
        return l

    pos = 0
    if length >= 32:
        # Seed-derived init constants wrap mod 2^64 by design; compute
        # in Python ints and mask, so numpy's scalar-overflow warning
        # machinery never fires on the intended wrap.
        acc1 = np.full(n, (seed_i + int(_P1) + int(_P2)) & int(_M64),
                       np.uint64)
        acc2 = np.full(n, (seed_i + int(_P2)) & int(_M64), np.uint64)
        acc3 = np.full(n, seed_i, np.uint64)
        acc4 = np.full(n, (seed_i - int(_P1)) & int(_M64), np.uint64)
        while pos + 32 <= length:
            _round_ip(acc1, lane64(pos), t)
            _round_ip(acc2, lane64(pos + 8), t)
            _round_ip(acc3, lane64(pos + 16), t)
            _round_ip(acc4, lane64(pos + 24), t)
            pos += 32
        _rotl_into(acc1, 1, h, t)
        for acc, r in ((acc2, 7), (acc3, 12), (acc4, 18)):
            _rotl_into(acc, r, s, t)
            np.add(h, s, out=h)
        for acc in (acc1, acc2, acc3, acc4):
            _merge_round_ip(h, acc, t, s)
    else:
        h.fill((seed_i + int(_P5)) & int(_M64))
    np.add(h, _U64(length), out=h)

    while pos + 8 <= length:
        # h <- rotl(h ^ round(0, lane), 27) * P1 + P4
        np.multiply(lane64(pos), _P2, out=t)
        _rotl_ip(t, 31, s)
        np.multiply(t, _P1, out=t)
        np.bitwise_xor(h, t, out=h)
        _rotl_ip(h, 27, s)
        np.multiply(h, _P1, out=h)
        np.add(h, _P4, out=h)
        pos += 8
    if pos + 4 <= length:
        l[:] = w32[:, pos // 4]          # u32 read, zero-extended
        np.multiply(l, _P1, out=t)
        np.bitwise_xor(h, t, out=h)
        _rotl_ip(h, 23, s)
        np.multiply(h, _P2, out=h)
        np.add(h, _P3, out=h)
        pos += 4
    while pos < length:
        l[:] = data[:, pos]              # single byte, zero-extended
        np.multiply(l, _P5, out=t)
        np.bitwise_xor(h, t, out=h)
        _rotl_ip(h, 11, s)
        np.multiply(h, _P1, out=h)
        pos += 1

    # Avalanche: h ^= h>>33; h*=P2; h^=h>>29; h*=P3; h^=h>>32.
    for shift, prime in ((33, _P2), (29, _P3), (32, None)):
        np.right_shift(h, _U64(shift), out=t)
        np.bitwise_xor(h, t, out=h)
        if prime is not None:
            np.multiply(h, prime, out=h)


def pack_key_matrix(keys: Sequence) -> tuple:
    """(matrix [N, W] uint8 zero-padded, lengths int64[N]) for a batch
    of str or bytes keys — the C-level pack feeding both the host
    vectorized digest and the device pipeline.

    numpy's fixed-width "S" conversion does the whole encode+pad in one
    C loop (no per-key Python), preserves embedded AND trailing NUL
    bytes, and refuses non-ASCII str (UnicodeEncodeError) — for the
    ASCII keys it accepts, len(str) == byte length, so `lengths` is
    exact even where the padding makes the matrix itself ambiguous."""
    n = len(keys)
    lengths = np.fromiter(map(len, keys), np.int64, count=n)
    width = int(lengths.max()) if n else 0
    if width == 0:
        return np.zeros((n, 0), np.uint8), lengths
    # Width rounded to 8 bytes: the digest reads u64 columns, and this
    # makes the pack itself the aligned zero-tailed layout xxh64_batch
    # consumes copy-free.
    width += (-width) % 8
    arr = np.array(keys, dtype=f"S{width}")
    return arr.view(np.uint8).reshape(n, width), lengths


def xxh64_keys(keys: Sequence, seed: int) -> np.ndarray:
    """XXH64 over variable-length str-or-bytes keys: one C-level pack
    into a padded byte matrix, then the grouped lane-parallel digest.
    No per-key Python work anywhere — this is what lets the batch beat
    the ~400-870ns/key C-extension loop by an order of magnitude
    instead of drowning in bucketing overhead."""
    if len(keys) == 0:
        return np.empty(0, np.uint64)
    try:
        mat, lengths = pack_key_matrix(keys)
    except UnicodeEncodeError:
        # Non-ASCII str keys: per-key utf-8 encode, then re-pack.  Rare
        # (cache keys are hex digests); correctness over speed here.
        mat, lengths = pack_key_matrix(
            [k.encode() if isinstance(k, str) else k for k in keys])
    return xxh64_grouped(mat, lengths, seed)


def xxh64_grouped(mat: np.ndarray, lengths: np.ndarray,
                  seed: int) -> np.ndarray:
    """Digest phase over a pack_key_matrix layout: vectorized length
    grouping (stable argsort), one lane-parallel digest per length
    class, results scattered back in input order.  Split out from
    xxh64_keys so the benchmark can time packing and digesting
    separately — they are different budgets (data layout vs hashing)."""
    n = mat.shape[0]
    out = np.empty(n, np.uint64)
    if n == 0:
        return out
    lo = int(lengths.min())
    if lo == int(lengths.max()):
        # Single length class (THE steady-state shape: fixed-width
        # cache-entry digests) — skip the grouping sort entirely.
        return xxh64_batch(mat, seed, lo)
    order = np.argsort(lengths, kind="stable")
    sl = lengths[order]
    group_starts = np.flatnonzero(np.diff(sl, prepend=-1))
    for gi, gs in enumerate(group_starts):
        ge = group_starts[gi + 1] if gi + 1 < len(group_starts) else n
        length = int(sl[gs])
        idxs = order[gs:ge]
        if len(idxs) == n:
            # Single length class (THE steady-state shape: fixed-width
            # cache-entry digests) — no gather, no copy: the digest
            # reads straight out of the pack.
            return xxh64_batch(mat, seed, length)
        aligned = length + (-length) % 8
        sub = np.ascontiguousarray(mat[idxs, :aligned]) if length else \
            np.zeros((len(idxs), 0), np.uint8)
        out[idxs] = xxh64_batch(sub, seed, length)
    return out
