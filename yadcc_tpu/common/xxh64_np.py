"""Vectorized XXH64 over batches of equal-length keys (numpy u64).

The Bloom control plane fingerprints MILLIONS of cache keys
(common/bloom.py); the per-key C-extension call costs ~870ns — 0.87s
per 1M-key batch, dwarfing the probe itself (round-2
artifacts/bloom_bench.json).  This module computes the identical
XXH64 digest lane-parallel over a [N, L] byte matrix: ~30 u64 vector
ops per 32-byte stripe amortized across the whole batch.

Bit-identical to the reference algorithm (public XXH64 spec, the same
one the `xxhash` wheel wraps); `tests/test_bloom_fast.py` cross-checks
against the C implementation over every tail-length class.  numpy's
u64 arithmetic wraps modulo 2^64, which is exactly the semantics the
algorithm needs.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence

import numpy as np

_P1 = np.uint64(0x9E3779B185EBCA87)
_P2 = np.uint64(0xC2B2AE3D27D4EB4F)
_P3 = np.uint64(0x165667B19E3779F9)
_P4 = np.uint64(0x85EBCA77C2B2AE63)
_P5 = np.uint64(0x27D4EB2F165667C5)
_M64 = np.uint64(0xFFFFFFFFFFFFFFFF)


def _rotl(x: np.ndarray, r: int) -> np.ndarray:
    r = np.uint64(r)
    return (x << r) | (x >> (np.uint64(64) - r))


def _round(acc: np.ndarray, lane: np.ndarray) -> np.ndarray:
    return _rotl(acc + lane * _P2, 31) * _P1


def _merge_round(h: np.ndarray, acc: np.ndarray) -> np.ndarray:
    return (h ^ _round(np.uint64(0), acc)) * _P1 + _P4


def _avalanche(h: np.ndarray) -> np.ndarray:
    h = (h ^ (h >> np.uint64(33))) * _P2
    h = (h ^ (h >> np.uint64(29))) * _P3
    return h ^ (h >> np.uint64(32))


def xxh64_batch(data: np.ndarray, seed: int) -> np.ndarray:
    """XXH64 of every row of a [N, L] uint8 matrix (one key per row,
    all the same length L), with the given seed.  Returns uint64[N]."""
    if data.ndim != 2 or data.dtype != np.uint8:
        raise ValueError("data must be a [N, L] uint8 matrix")
    n, length = data.shape
    seed = np.uint64(seed & 0xFFFFFFFFFFFFFFFF)

    # All u64 reads land on 8-byte offsets (stripes consume 32, the
    # tail loop 8 at a time) and the sole u32 read on a 4-byte offset,
    # so pad the matrix to an 8-byte multiple once and reinterpret:
    # each read is then one contiguous little-endian column view.
    pad = (-length) % 8
    padded = np.ascontiguousarray(
        np.pad(data, ((0, 0), (0, pad))) if pad else data)
    w64 = padded.view("<u8")
    w32 = padded.view("<u4")

    def u64_at(off: int) -> np.ndarray:
        return w64[:, off // 8].astype(np.uint64, copy=True)

    def u32_at(off: int) -> np.ndarray:
        return w32[:, off // 4].astype(np.uint64)

    pos = 0
    if length >= 32:
        acc1 = np.full(n, seed + _P1 + _P2, np.uint64)
        acc2 = np.full(n, seed + _P2, np.uint64)
        acc3 = np.full(n, seed, np.uint64)
        acc4 = np.full(n, seed - _P1, np.uint64)
        while pos + 32 <= length:
            acc1 = _round(acc1, u64_at(pos))
            acc2 = _round(acc2, u64_at(pos + 8))
            acc3 = _round(acc3, u64_at(pos + 16))
            acc4 = _round(acc4, u64_at(pos + 24))
            pos += 32
        h = (_rotl(acc1, 1) + _rotl(acc2, 7)
             + _rotl(acc3, 12) + _rotl(acc4, 18))
        h = _merge_round(h, acc1)
        h = _merge_round(h, acc2)
        h = _merge_round(h, acc3)
        h = _merge_round(h, acc4)
    else:
        h = np.full(n, seed + _P5, np.uint64)
    h = h + np.uint64(length)

    while pos + 8 <= length:
        h = _rotl(h ^ _round(np.uint64(0), u64_at(pos)), 27) * _P1 + _P4
        pos += 8
    if pos + 4 <= length:
        h = _rotl(h ^ (u32_at(pos) * _P1), 23) * _P2 + _P3
        pos += 4
    while pos < length:
        h = _rotl(h ^ (data[:, pos].astype(np.uint64) * _P5), 11) * _P1
        pos += 1
    return _avalanche(h)


def xxh64_keys(keys: Sequence[bytes], seed: int) -> np.ndarray:
    """XXH64 over variable-length keys: group rows by length, run each
    group lane-parallel, scatter results back in order."""
    out = np.empty(len(keys), np.uint64)
    by_len: dict = {}
    for i, k in enumerate(keys):
        by_len.setdefault(len(k), []).append(i)
    for length, idxs in by_len.items():
        if length == 0:
            mat = np.zeros((len(idxs), 0), np.uint8)
        else:
            mat = np.frombuffer(
                b"".join(keys[i] for i in idxs), np.uint8
            ).reshape(len(idxs), length)
        out[np.asarray(idxs)] = xxh64_batch(mat, seed)
    return out
