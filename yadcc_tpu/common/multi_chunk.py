r"""Multi-chunk framing codec shared by the client and the local daemon.

Format (reference yadcc/daemon/local/README.md:13-27): a first line of
comma-separated decimal chunk lengths terminated by \r\n, followed by the
chunks' bytes concatenated:

    b"2,10\r\nXX0123456789"  ==  [b"XX", b"0123456789"]

An empty chunk list encodes as just b"\r\n".

Two API tiers:

* ``make_multi_chunk_payload`` / ``try_parse_multi_chunk_views`` — the
  zero-copy tier: building returns a :class:`~.payload.Payload` whose
  segments are the header plus the callers' own chunk buffers, and
  parsing returns ``memoryview`` slices into the received buffer.  The
  data plane uses these.
* ``make_multi_chunk`` / ``try_parse_multi_chunk`` — the materializing
  compat tier (byte-identical wire format), kept for callers that need
  owned ``bytes``; their copies are charged to the payload copy meter.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

from .payload import Payload, Segment, count_copy

# The header scan reads the buffer in small windows so locating "\r\n"
# never materializes a multi-MB frame (views must stay zero-copy).
_HEADER_SCAN_WINDOW = 4096


def _find_crlf(mv: memoryview) -> int:
    n = len(mv)
    pos = 0
    while pos < n:
        # +1 overlap so a "\r|\n" split across windows is still found.
        window = bytes(mv[pos:pos + _HEADER_SCAN_WINDOW + 1])
        i = window.find(b"\r\n")
        if i >= 0:
            return pos + i
        if pos + len(window) >= n:
            return -1
        pos += _HEADER_SCAN_WINDOW
    return -1


def make_multi_chunk_payload(
        chunks: Sequence[Union[Segment, Payload]]) -> Payload:
    """Gather form: header segment + the chunk buffers themselves."""
    header = ",".join(str(len(c)) for c in chunks).encode() + b"\r\n"
    return Payload((header, *chunks))


def try_parse_multi_chunk_views(data) -> Optional[List[memoryview]]:  # ytpu: sanitizes(framing)
    """Zero-copy parse: chunk bodies are views into ``data``.

    ``data`` may be ``bytes``, ``bytearray`` or a ``memoryview`` (e.g.
    an RPC attachment still backed by its frame).  The views pin that
    buffer alive; callers wanting owned bytes use the compat parser.
    """
    mv = memoryview(data)
    eol = _find_crlf(mv)
    if eol < 0:
        return None
    header = bytes(mv[:eol])
    body = mv[eol + 2:]
    if not header:
        return [] if len(body) == 0 else None
    try:
        lengths = [int(x) for x in header.split(b",")]
    except ValueError:
        return None
    if any(l < 0 for l in lengths) or sum(lengths) != len(body):
        return None
    chunks: List[memoryview] = []
    off = 0
    for l in lengths:
        chunks.append(body[off:off + l])
        off += l
    return chunks


def make_multi_chunk(chunks: Sequence[bytes]) -> bytes:
    return make_multi_chunk_payload(chunks).join()


def try_parse_multi_chunk(data: bytes) -> Optional[List[bytes]]:
    views = try_parse_multi_chunk_views(data)
    if views is None:
        return None
    count_copy(sum(len(v) for v in views))
    return [bytes(v) for v in views]
