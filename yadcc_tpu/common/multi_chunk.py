r"""Multi-chunk framing codec shared by the client and the local daemon.

Format (reference yadcc/daemon/local/README.md:13-27): a first line of
comma-separated decimal chunk lengths terminated by \r\n, followed by the
chunks' bytes concatenated:

    b"2,10\r\nXX0123456789"  ==  [b"XX", b"0123456789"]

An empty chunk list encodes as just b"\r\n".
"""

from __future__ import annotations

from typing import List, Optional, Sequence


def make_multi_chunk(chunks: Sequence[bytes]) -> bytes:
    header = ",".join(str(len(c)) for c in chunks).encode()
    return header + b"\r\n" + b"".join(chunks)


def try_parse_multi_chunk(data: bytes) -> Optional[List[bytes]]:
    eol = data.find(b"\r\n")
    if eol < 0:
        return None
    header = data[:eol]
    body = memoryview(data)[eol + 2 :]
    if not header:
        return [] if len(body) == 0 else None
    try:
        lengths = [int(x) for x in header.split(b",")]
    except ValueError:
        return None
    if any(l < 0 for l in lengths) or sum(lengths) != len(body):
        return None
    chunks: List[bytes] = []
    off = 0
    for l in lengths:
        chunks.append(bytes(body[off : off + l]))
        off += l
    return chunks
