"""Protocol version ledger.

Parity with reference yadcc/daemon/common_flags.cc:41-63: a monotonically
increasing integer, checked by the scheduler (--min_daemon_version) and
carried in grant requests, gates protocol-incompatible daemons out of the
pool.  Bump on every wire-visible change and record it here.

History:
  1: initial wire protocol of the TPU-native rebuild.
  2: cache_control=2 carries Refill semantics end to end (daemon.proto
     disable_cache_fill / local.proto cache-control tri-state) and
     local.proto's ignore-timestamp-macros knob joins the task
     submission surface.  Consolidates the two wire-visible additions
     that landed without a bump (commits 796867e, f6c2572) — recorded
     retroactively per VERDICT r3 "version-ledger discipline".
"""

VERSION_FOR_UPGRADE = 2

# Human-readable build stamp served by /local/get_version.
BUILT_AT = "yadcc-tpu dev"
