"""Protocol version ledger.

Parity with reference yadcc/daemon/common_flags.cc:41-63: a monotonically
increasing integer, checked by the scheduler (--min_daemon_version) and
carried in grant requests, gates protocol-incompatible daemons out of the
pool.  Bump on every wire-visible change and record it here.

History:
  1: initial wire protocol of the TPU-native rebuild.
"""

VERSION_FOR_UPGRADE = 1

# Human-readable build stamp served by /local/get_version.
BUILT_AT = "yadcc-tpu dev"
