# Top-level convenience targets.  `make check` is the cold-clone gate
# (native build + tier-1 pytest) that mirrors the reference's per-push
# CI (yadcc .github/workflows/build-and-test.yml) — see tools/ci.sh.

.PHONY: check native clean

check:
	bash tools/ci.sh

native:
	$(MAKE) -C native

clean:
	$(MAKE) -C native clean
