# Top-level convenience targets.  `make check` is the cold-clone gate
# (lint + native build + tier-1 pytest) that mirrors the reference's
# per-push CI (yadcc .github/workflows/build-and-test.yml) — see
# tools/ci.sh.  `make lint` is the static tier alone: the
# concurrency/jit analyzer (doc/static_analysis.md) plus shellcheck
# over the ops scripts where the tool is installed.

.PHONY: check lint native clean

check:
	bash tools/ci.sh

lint:
	python -m yadcc_tpu.analysis yadcc_tpu
	@if command -v shellcheck >/dev/null 2>&1; then \
	  shellcheck tools/*.sh; \
	else \
	  echo "shellcheck not installed; skipping shell lint"; \
	fi

native:
	$(MAKE) -C native

clean:
	$(MAKE) -C native clean
