"""North-star benchmark: scheduler assignment throughput on device.

Simulates the BASELINE.json target scenario — a 5000-servant pool with
heterogeneous capacities and environments, grant requests arriving in
micro-batches — and measures end-to-end dispatch throughput through the
same path the production JaxGroupedPolicy uses (per-batch descriptor
upload + one jitted threshold-search per descriptor group + counts
download), plus per-batch latency percentiles.  The loop is PIPELINED:
`running` stays device-resident across batches and counts stream back
via async D2H with a window of batches in flight — the production
dispatch shape, and the only honest measurement on this harness's
remote-attached accelerator, where every synchronous D2H fetch pays a
flat ~70ms tunnel round-trip (reported as tunnel_d2h_rtt_ms; on a
host-attached deployment it is microseconds).

Target (BASELINE.md): >= 50,000 assignments/sec with p99 dispatch
latency < 2ms.  The child prints a complete JSON line after the
headline sections and again after each Pallas A/B; the LAST line is
the result (the orchestrator selects it, including from the partial
stdout of a timed-out child, so a late wedge can't destroy earlier
measurements).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _make_groups(rng, T, G, E_WORDS):
    """One micro-batch's request groups; sizes sum exactly to T."""
    envs = rng.integers(0, E_WORDS * 32, G)
    sizes = np.full(G, T // G, np.int32)
    sizes[: T % G] += 1
    return [(int(e), 1, -1, int(m)) for e, m in zip(envs, sizes)]


def _occupancy_trimmer(static, target: float = 0.55):
    """Shared steady-state model: a closure retiring grants (the
    FreeTask stream) so occupancy hovers around `target` — used
    identically by the headline loop and both Pallas A/Bs so their
    numbers stay comparable.

    Fully device-resident: the original version synced occupancy to the
    host every batch (`device_get(running.sum())`), which on a remote-
    attached accelerator costs a full D2H round-trip (~70ms on the axon
    tunnel, measured) and single-handedly capped the pipeline.  The
    occupancy test now rides inside the jitted trim itself."""
    import jax
    import jax.numpy as jnp

    capacity = np.asarray(static["capacity"])
    alive = np.asarray(static["alive"])
    total_capacity = int(capacity[alive].sum())
    target_occ = jnp.float32(target * total_capacity)

    @jax.jit
    def trim(running):
        occ = running.sum().astype(jnp.float32)
        frac = jnp.where(occ > target_occ,
                         (occ - target_occ) / jnp.maximum(occ, 1.0),
                         0.0)
        freed = (running.astype(jnp.float32) * frac).astype(jnp.int32)
        return jnp.maximum(running - freed, 0)

    return trim


def _measure_d2h_rtt(n: int = 5) -> float:
    """Median round-trip of a fresh single-scalar device->host transfer.
    On co-located hardware this is microseconds; on the harness's
    tunnelled accelerator it is a flat ~70ms per synchronous fetch —
    the number that makes pipelining (not per-batch sync) the only
    honest way to measure dispatch throughput here."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    x = jnp.int32(0)
    rtts = []
    for _ in range(n):
        x = f(x)
        t0 = time.perf_counter()
        int(x)                       # fresh result: forced D2H sync
        rtts.append(time.perf_counter() - t0)
    rtts.sort()
    return rtts[len(rtts) // 2] * 1000.0


def _pipelined_run(step_fn, make_batch_fn, running, trim,
                   batches: int, warmup: int, window: int,
                   count_fn=None):
    """The shared measurement harness: drive `step_fn` (upload ->
    kernel -> async D2H) with at most `window` batches in flight, the
    production dispatch shape for a device that is not host-attached.
    `window` is the cap on concurrently in-flight batches: at window 1
    each batch is submitted and drained before the next is built — one
    batch truly alone in the pipeline (the light-load adaptive-dispatch
    shape), so its latency is upload + kernel + download only, with no
    next-batch host work folded in.

    Per-batch latency is submit -> counts-on-host (includes the real
    transport RTT); throughput is completed grants / wall time.
    Returns (running, grants/s, latencies_s, elapsed_s)."""
    import collections

    import numpy as np

    inflight = collections.deque()
    granted = 0
    latencies = []
    drain_times = []

    if count_fn is None:
        count_fn = lambda arr: int(arr.sum())   # grant-count vectors

    def drain_one():
        nonlocal granted
        t_submit, result = inflight.popleft()
        arr = np.asarray(result)           # ready or nearly so
        now = time.perf_counter()
        latencies.append(now - t_submit)
        drain_times.append(now)
        granted += count_fn(arr)

    # Warmup flows through the same pipeline, then the clock starts.
    for i in range(warmup):
        counts, running = step_fn(make_batch_fn(i), running)
        counts.copy_to_host_async()
        if trim is not None:        # None = trim fused into step_fn
            running = trim(running)
        inflight.append((time.perf_counter(), counts))
        if len(inflight) >= window:
            drain_one()
    while inflight:
        drain_one()
    granted, latencies, drain_times = 0, [], []

    t_start = time.perf_counter()
    for i in range(batches):
        t0 = time.perf_counter()
        counts, running = step_fn(make_batch_fn(i), running)
        counts.copy_to_host_async()
        if trim is not None:
            running = trim(running)
        inflight.append((t0, counts))
        if len(inflight) >= window:
            drain_one()
    while inflight:
        drain_one()
    elapsed = time.perf_counter() - t_start
    return running, granted / elapsed, latencies, elapsed, drain_times


def main() -> None:
    # Same CPU priority a production scheduler daemon runs at (systemd
    # Nice=-10 is standard for latency-critical control planes): on
    # this harness's single shared core, background work would
    # otherwise write its own pauses into our tail percentiles.
    try:
        os.setpriority(os.PRIO_PROCESS, 0, -10)
    except (OSError, AttributeError):
        pass
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from yadcc_tpu.ops import assignment as asn

    S = int(os.environ.get("BENCH_POOL", 5120))   # servant slots
    T = int(os.environ.get("BENCH_BATCH", 512))   # tasks per micro-batch
    E_WORDS = 8       # 256 environments
    WARMUP = 3
    BATCHES = int(os.environ.get("BENCH_BATCHES", 200))

    rng = np.random.default_rng(42)
    alive = rng.random(S) < 0.95
    capacity = rng.integers(8, 64, S).astype(np.int32)  # heterogeneous
    dedicated = rng.random(S) < 0.3
    version = np.ones(S, np.int32)
    env_bitmap = rng.integers(0, 2**32, (S, E_WORDS),
                              dtype=np.uint64).astype(np.uint32)

    from yadcc_tpu.ops import assignment_grouped as asg

    # A micro-batch fans T requests over a handful of distinct compiler
    # environments (one build floods one env); the grouped kernel
    # resolves each group with one parallel threshold search.
    G = int(os.environ.get("BENCH_GROUPS", 4))
    G_PAD = asg.group_pad(G)  # the exact shape policy production uses


    # The pool lives on the device: static arrays (capacity, envs, ...)
    # upload once and change only on heartbeat deltas; `running` stays
    # device-resident across cycles, updated in-kernel.  This is the
    # production shape — per-batch work is [host descriptors -> kernel
    # -> counts download], not a full pool re-upload.
    static = dict(
        alive=jnp.asarray(alive),
        capacity=jnp.asarray(capacity),
        dedicated=jnp.asarray(dedicated),
        version=jnp.asarray(version),
        env_bitmap=jnp.asarray(env_bitmap),
    )
    running = jnp.zeros(S, jnp.int32)

    # Steady state: the FreeTask stream retires roughly one grant per
    # grant issued (trim applied off the timed path) — occupancy hovers
    # around the target instead of sawtoothing to empty.
    trim = _occupancy_trimmer(static)

    on_tpu = jax.devices()[0].platform == "tpu"

    # The pipelined dispatch loop: `running` lives on device the whole
    # time, counts stream back via async D2H with WINDOW batches in
    # flight.  The window exists to hide the device->host transport
    # RTT, so it is sized from the MEASURED RTT — mirroring the
    # dispatcher's own rule (scheduler/entry.py resolve_pipeline_depth:
    # pipelined on accelerators, synchronous on host platforms):
    #   * remote-attached accelerator (~70ms tunnel RTT here): a deep
    #     window is the only honest measurement — sync would measure
    #     the tunnel, not the kernel;
    #   * host platform / co-located (RTT ~us): window 1.  Compute
    #     shares the host's cores, so in-flight depth adds queueing
    #     jitter and hides nothing — the synchronous cycle is both
    #     faster and tighter (measured: this box is single-core).
    rtt_ms = _measure_d2h_rtt()
    if "BENCH_WINDOW" in os.environ:
        WINDOW = int(os.environ["BENCH_WINDOW"])
    elif on_tpu and rtt_ms >= 1.0:
        WINDOW = 64                  # remote tunnel: hide the RTT
    elif on_tpu:
        WINDOW = 4                   # co-located chip: overlap host+dev
    else:
        WINDOW = 1                   # host platform: sync is optimal
    T_PAD = asg.task_pad(T)

    # The production JaxGroupedPolicy device path, matching its
    # platform choice (policy._decide_expand).  On TPU, fully fused:
    # ONE [4, G] descriptor upload, ONE dispatch (threshold search +
    # on-device expansion + the FreeTask trim), ONE int32[T] picks
    # download (2KB, vs the 80KB counts matrix; every extra device op
    # costs ~1ms of dispatch on a remote-attached accelerator).  On
    # CPU, the counts path — transfers are free there and the dense
    # T x S expansion compare is pure overhead the production policy
    # skips too.
    if on_tpu:
        @jax.jit
        def step(packed, running):
            picks, new_running = asg.assign_grouped_picks_packed(
                asn.PoolArrays(running=running, **static), packed, T_PAD)
            return picks, trim(new_running)

        count_fn = lambda arr: int((arr >= 0).sum())
    else:
        @jax.jit
        def step(packed, running):
            counts, new_running = asg.assign_grouped(
                asn.PoolArrays(running=running, **static),
                asg.unpack_grouped(packed))
            return counts, trim(new_running)

        count_fn = lambda arr: int(arr.sum())

    # The workload (which envs, how many tasks) is pre-generated: in
    # production those descriptors arrive in the request queue; only
    # the dispatcher's own work — packing (`make_grouped_packed`, one
    # H2D) and the kernel — belongs inside the measured cycle.  RNG
    # time is harness noise, not dispatch latency.
    LAT_BATCHES = int(os.environ.get("BENCH_LAT_BATCHES", 400))
    n_workload = max(BATCHES, LAT_BATCHES) + WARMUP + 16
    workload = [_make_groups(rng, T, G, E_WORDS)
                for _ in range(n_workload)]

    def mkbatch(i):
        return asg.make_grouped_packed(workload[i % n_workload],
                                       pad_to=G_PAD)

    # Measured loops run under the same GC configuration the scheduler
    # serves with (utils/gctune.py, wired in scheduler/entry.py): the
    # automatic cyclic collector's stop-the-world passes are multi-ms
    # p99 outliers that production takes off the grant path, so the
    # benchmark must too — this measures production, it doesn't hide
    # harness cost.
    from yadcc_tpu.utils import gctune

    # Each section runs BENCH_PASSES times and reports the MEDIAN of
    # the per-pass statistics.  A single 0.3s measurement window on a
    # shared box (this harness: ONE core, with capture loops / drivers
    # running concurrently) is at the mercy of unrelated background
    # work; the median across passes estimates the service's own tail
    # — the quantity under test — while the per-pass values are kept
    # in the output for inspection.
    PASSES = max(1, int(os.environ.get("BENCH_PASSES", 3)))

    thr_passes, svc_passes, floor_passes = [], [], []
    with gctune.guard():
        for p in range(PASSES):
            running, per_sec_p, _, elapsed, drain_times = _pipelined_run(
                step, mkbatch, running, trim=None,
                batches=BATCHES,
                warmup=(WARMUP + 5) if p == 0 else 2,
                window=WINDOW, count_fn=count_fn)
            thr_passes.append(per_sec_p)
            # Per-batch pipeline service time: what each batch adds to
            # the steady-state stream — the latency floor a
            # host-attached deploy would see.
            svc_passes.append(elapsed * 1000.0 / max(1, BATCHES))
            # The BASELINE p99<2ms target, measured as the p99 of
            # steady-state per-batch completion intervals: each
            # interval is what ONE batch adds to the dispatch stream
            # once the pipeline is full — the p99 dispatch latency a
            # CO-LOCATED deployment observes (its transport RTT is
            # microseconds; this harness's tunnel RTT is reported
            # separately in tunnel_d2h_rtt_ms).  The first `window`
            # drains land back-to-back while the pipeline fills; only
            # steady-state intervals count.
            deltas = np.diff(np.array(drain_times))[max(1, WINDOW):]
            if deltas.size:
                floor_passes.append(
                    float(np.percentile(deltas * 1000, 99)))
    per_sec = float(np.median(thr_passes))
    service_ms = float(np.median(svc_passes))
    p99_floor_ms = (float(np.median(floor_passes))
                    if floor_passes else None)

    # Latency is measured in a separate SOLO run: with a deep window,
    # submit->drain latency is just window x service time (a knob, not
    # a property of the kernel).  Window 1 is the light-load adaptive-
    # dispatch shape — one batch alone in the pipeline (submitted and
    # drained before the next exists) — so each sample is exactly
    # upload + kernel + download: the transport RTT on this harness's
    # tunnel (see tunnel_d2h_rtt_ms), microseconds co-located.
    LAT_WINDOW = 1
    lat_passes = []
    with gctune.guard():
        for p in range(PASSES):
            running, _, latencies, _, _ = _pipelined_run(
                step, mkbatch, running, trim=None,
                batches=LAT_BATCHES, warmup=8 if p == 0 else 2,
                window=LAT_WINDOW, count_fn=count_fn)
            lat_passes.append(
                float(np.percentile(np.array(latencies) * 1000, 99)))
    p99_ms = float(np.median(lat_passes))
    target = 50_000.0

    # Secondary metric: grants/sec through the FULL TaskDispatcher —
    # incremental snapshot, policy kernel, lease bookkeeping, apply
    # phase — not just the raw kernel.  5000 live servants, 512-request
    # backlog per cycle (BASELINE "p99 @5k workers" scenario).
    # BENCH_SECTIONS=headline skips the (minutes-long) full-dispatcher
    # and heartbeat sections — used by the pool-size sweep, where only
    # the kernel-path scaling is under test.
    headline_only = os.environ.get("BENCH_SECTIONS") == "headline"

    # v10: the device-resident dispatch path (doc/scheduler.md
    # "Device-resident dispatch").  The microbench drives the fused
    # scatter->fold->assign step with the pool donated across launches
    # — the accelerator IS the hot loop; the policy-stage rig then
    # shows what that does to the dispatcher's own "policy" stage.
    try:
        resident = _device_resident_throughput(S, E_WORDS)
    except Exception as e:
        resident = {"error": f"{type(e).__name__}: {e}"[:300]}
    resident_stage = None
    if not headline_only:
        try:
            resident_stage = _resident_policy_stage_metrics()
        except Exception as e:
            resident_stage = {"error": f"{type(e).__name__}: {e}"[:300]}

    disp_per_sec = None if headline_only \
        else _dispatcher_cycle_throughput()
    disp_pipe_per_sec = None if headline_only \
        else _dispatcher_pipelined_throughput()
    beats_per_sec = None if headline_only else _heartbeat_throughput()
    bloom_fp = None if headline_only else _bloom_fingerprint_metrics()

    # The RTT regime the dispatcher sections ran under.  Pipelined
    # dispatch exists to hide the device->host round-trip; on a host
    # platform (or co-located chip) there is no RTT to hide, so the
    # pipelined number is EXPECTED to lose to the synchronous one —
    # an unlabeled "11.1k pipelined vs 88.8k sync" invites misreading
    # the design as a regression (VERDICT r5 Weak #3).
    if not on_tpu:
        rtt_regime = "host"
    elif rtt_ms >= 1.0:
        rtt_regime = "remote_tunnel"
    else:
        rtt_regime = "colocated"

    # Host-side data-plane throughput (tools/dataplane_bench): the
    # zero-copy copy-path composite at 1MB.  Cheap, host-only, and a
    # regression canary for the byte path riding along with the
    # device numbers.
    try:
        from yadcc_tpu.tools.dataplane_bench import \
            quick_dataplane_mb_per_sec

        dataplane_mb = round(quick_dataplane_mb_per_sec(), 1)
    except Exception:
        dataplane_mb = None

    # End-to-end jit-offload throughput (tools/cluster_sim --workload
    # jit, fake worker): submissions/s through the full loopback farm.
    # A control-plane canary for the second workload riding along with
    # the scheduler numbers.
    try:
        from yadcc_tpu.tools.cluster_sim import quick_jit_compiles_per_sec

        jit_cps = round(quick_jit_compiles_per_sec(), 1)
    except Exception:
        jit_cps = None

    # Fan-out workload canaries (tools/cluster_sim --workload aot /
    # autotune, doc/workloads.md): topology results delivered per
    # second through the fan-out path, and the sweep corpus' dedup
    # ratio (fraction of child resolutions that cost no servant
    # compile — the cluster-wide "measure once" claim).
    try:
        from yadcc_tpu.tools.cluster_sim import \
            quick_aot_fanout_compiles_per_sec

        aot_cps = round(quick_aot_fanout_compiles_per_sec(), 1)
    except Exception:
        aot_cps = None
    try:
        from yadcc_tpu.tools.cluster_sim import \
            quick_autotune_sweep_dedup_ratio

        autotune_dedup = round(quick_autotune_sweep_dedup_ratio(), 3)
    except Exception:
        autotune_dedup = None

    # Sharded control-plane canary (tools/pod_sim --shards,
    # doc/scheduler.md "Sharded control plane"): grants/s through a
    # small 4-shard ShardRouter on the full RPC grant path — the
    # in-harness twin of artifacts/pod_sim_sharded.json's headline.
    try:
        from yadcc_tpu.tools.pod_sim import \
            quick_sharded_assignments_per_sec

        sharded_aps = round(quick_sharded_assignments_per_sec(), 1)
    except Exception:
        sharded_aps = None

    # RPC front-end canaries (rpc/aio_server.py, doc/benchmarks.md
    # "RPC front end"): concurrent long-poll connections a small aio
    # connection storm sustains with zero errors, and grant_call p99
    # through the aio front end's parked WaitForStartingTask on the
    # pod_sim pump rig — the in-harness twins of
    # artifacts/rpc_frontend_ab.json.
    try:
        from yadcc_tpu.tools.cluster_sim import \
            quick_storm_concurrent_connections

        storm_conns = quick_storm_concurrent_connections()
    except Exception:
        storm_conns = None
    try:
        from yadcc_tpu.tools.pod_sim import quick_aio_grant_call_p99_ms

        aio_grant_p99 = quick_aio_grant_call_p99_ms()
    except Exception:
        aio_grant_p99 = None

    # Full-async serving path canaries (ISSUE 16, doc/benchmarks.md
    # "RPC front end"): the accept-p99 ratio of a small aio storm at
    # --accept-loops 4 over 1 (must stay ~flat), and the parked
    # WaitForCompilationOutput continuations a small servant rig holds
    # at once with zero extra OS threads — the in-harness twins of
    # artifacts/cluster_sim_50k.json.
    try:
        from yadcc_tpu.tools.cluster_sim import quick_accept_loops_scaling

        accept_scaling = quick_accept_loops_scaling()
    except Exception:
        accept_scaling = None
    try:
        from yadcc_tpu.tools.cluster_sim import \
            quick_servant_parked_waiters

        servant_parked = quick_servant_parked_waiters()
    except Exception:
        servant_parked = None

    # Hostile-world survival canaries (tools/scenarios.py,
    # doc/robustness.md): the p99 latency of an explicit REJECT verdict
    # under a smoke 4x-overload ladder storm (a rejection is an
    # immediate answer, not a queue wait), and the compile success rate
    # — local fallback counted — under a smoke flaky-servant run.
    try:
        from yadcc_tpu.tools.scenarios import quick_hostile_metrics

        hostile = quick_hostile_metrics()
    except Exception:
        hostile = {}

    # Three-level cache canaries (tools/scenarios.py cold-region smoke,
    # doc/benchmarks.md "Cold-region rebuild"): the hit rate a cold
    # region reaches purely through async L3 read-through promotion,
    # and the prefetch arm's wall time to 90% of the warm region's
    # steady hit rate.
    try:
        from yadcc_tpu.tools.scenarios import quick_coldregion_metrics

        coldregion = quick_coldregion_metrics()
    except Exception:
        coldregion = {}

    # Scored-spillover canaries (tools/scenarios.py spill-affinity
    # smoke, doc/benchmarks.md "Scored spillover placement"): the
    # scored arm's post-spill cache hit rate and the p99 cost of one
    # scored placement decision, device launch included.
    try:
        from yadcc_tpu.tools.scenarios import quick_spill_affinity_metrics

        spill_affinity = quick_spill_affinity_metrics()
    except Exception:
        spill_affinity = {}

    # Multi-tenant QoS canaries (tools/scenarios.py noisy-neighbor +
    # cache-poisoning smokes, doc/tenancy.md): the victim tenant's
    # fair-share ratio against a 100-pid adversary and the
    # cryptographic cache-isolation proof bit.
    try:
        from yadcc_tpu.tools.scenarios import quick_tenancy_metrics

        tenancy = quick_tenancy_metrics()
    except Exception:
        tenancy = {}

    result = {
        "metric": "scheduler_assignments_per_sec_5k_workers",
        # Version 15 (r20+): adds `victim_tenant_slo_share` (the
        # victim tenant's share of a shared grant queue under a
        # 100-pid noisy neighbor in a smoke noisy-neighbor run —
        # 1.0 means the two-level stride held the tenant boundary
        # exactly) and `cross_tenant_isolation_ok` (1 iff the smoke
        # cache-poisoning run proved cross-namespace reads AND
        # poison plants both fail against tenant-scoped keys;
        # doc/tenancy.md).  Every v14 field is still emitted.
        # Version 14 (r19+): adds `placement_warm_hit_rate` (post-spill
        # cache hit rate of the scored-placement arm in a smoke
        # spill-affinity run — spills landing on the warm peer despite
        # its higher load) and `placement_score_p99_us` (p99 of one
        # scored spill decision through the fused cells x tasks device
        # launch, signal reads and readback included; tools/scenarios.py
        # spill-affinity, doc/benchmarks.md "Scored spillover
        # placement").  Every v13 field is still emitted.
        # Version 13 (r18+): adds `l3_read_through_hit_rate` (final hit
        # rate of the prefetch-OFF cold-region arm — a region with
        # empty L1/L2 warming purely via the shared L3 bucket's async
        # read-through promotion) and `prefetch_time_to_warm_s` (wall
        # seconds for the trace-prefetched arm to hold 90% of the warm
        # region's steady hit rate over a rolling window;
        # tools/scenarios.py cold-region smoke, doc/benchmarks.md
        # "Cold-region rebuild").  Every v12 field is still emitted.
        # Version 12 (r17+): adds `accept_loops_scaling` (accept p99
        # ratio of a small aio connection storm at --accept-loops 4
        # over 1 — the SO_REUSEPORT AioServerGroup must hold the accept
        # tail ~flat) and `servant_parked_waiters` (parked
        # WaitForCompilationOutput continuations a small aio servant
        # rig holds at once with ZERO extra OS threads — the full-async
        # serving path's park claim, tools/cluster_sim --servant-park;
        # doc/benchmarks.md "RPC front end").  Every v11 field is still
        # emitted.
        # Version 11 (r16+): adds `failover_time_ms` (kill-to-first-
        # granted-RPC through the warm-standby takeover in a smoke
        # cell-kill run, tools/scenarios.py; doc/robustness.md
        # "Failover state machine") and `cell_kill_success_rate` (fleet
        # compile success across that kill, local fallback counted).
        # Every v10 field is still emitted.
        # Version 10 (r15+): adds `device_resident_assignments_per_sec`
        # (the fused device-resident dispatch step at the production
        # task cap — pool donated across launches, heartbeat deltas
        # scattered in, only results downloaded; detail in
        # `device_resident`), `policy_stage_p99_us` (host-side policy
        # stage p99 through the full pipelined dispatcher running the
        # resident policy; detail in `resident_policy_stage`), and the
        # Pallas A/Bs now run on EVERY platform — interpret mode on
        # CPU — so `pallas_ab`/`pallas_grouped_ab` are non-null with a
        # `mode` label.  Every v9 field is still emitted.
        # Version 9 (r14+): adds `concurrent_connections` (idle
        # long-poll clients a small aio-front-end connection storm
        # sustains with zero errors, tools/cluster_sim --clients) and
        # `grant_call_p99_ms` (grant RPC p99 through the aio front
        # end's parked WaitForStartingTask on the pod_sim pump rig) —
        # the event-loop front end canaries (doc/benchmarks.md "RPC
        # front end").  Every v8 field is still emitted.
        # Version 8 (r13+): adds `sharded_assignments_per_sec` — the
        # sharded-control-plane canary (a 4-shard ShardRouter smoke
        # through the full RPC grant path, tools/pod_sim;
        # doc/benchmarks.md "Sharded control plane").  Every v7 field
        # is still emitted.
        # Version 7 (r12+): adds `aot_fanout_compiles_per_sec` and
        # `autotune_sweep_dedup_ratio` — the fan-out workload canaries
        # (tools/cluster_sim --workload aot / autotune smoke runs;
        # doc/benchmarks.md "Fan-out workloads").  Every v6 field is
        # still emitted.
        # Version 6 (r11+): adds `overload_reject_p99_ms` and
        # `survival_compile_success_rate` from the hostile-world
        # scenario harness (tools/scenarios.py smoke runs of the
        # overload-ladder and flaky-servant scenarios;
        # doc/robustness.md).
        # Version 5 (r09+): adds `jit_compiles_per_sec` — end-to-end
        # jit-offload submissions/s through the loopback farm with the
        # deterministic fake worker (tools/cluster_sim --workload jit;
        # doc/benchmarks.md "Jit offload").
        # Version 4 (r07+): adds `dataplane_mb_per_sec` (zero-copy
        # copy-path composite at 1MB, tools/dataplane_bench stage
        # definitions — see doc/benchmarks.md "Data plane").
        # Version 3 (r06+): adds `dispatcher_rtt_regime` (see above)
        # and runs the full-dispatcher sections against the
        # incremental prepared-snapshot dispatcher.  Version 2: the
        # pipelined harness drains at len(inflight) >= window (was >),
        # so `pipeline_window` is the true cap on in-flight batches.
        # r01-r05 artifacts measured one extra batch in flight at the
        # same nominal window — do not compare r06+ numbers against
        # them at equal window settings without accounting for that.
        "harness_version": 15,
        "value": round(per_sec, 1),
        "unit": "assignments/s",
        "vs_baseline": round(per_sec / target, 3),
        "p99_batch_latency_ms": round(p99_ms, 3),
        "latency_mode_window": LAT_WINDOW,
        "latency_samples": LAT_BATCHES,
        "p99_latency_passes": [round(x, 3) for x in lat_passes],
        "p99_floor_passes": [round(x, 3) for x in floor_passes],
        "gc_guard": True,
        "pipeline_service_ms_per_batch": round(service_ms, 3),
        # BASELINE p99 target, co-located floor: p99 of steady-state
        # per-batch completion intervals in the deep-window run
        # (excludes this harness's tunnel RTT, which a co-located
        # deployment does not pay; see tunnel_d2h_rtt_ms).
        "p99_batch_service_ms_colocated_floor": (
            round(p99_floor_ms, 3) if p99_floor_ms is not None
            else None),
        "tunnel_d2h_rtt_ms": round(rtt_ms, 2),
        "pipeline_window": WINDOW,
        "batch_size": T,
        "pool_size": S,
        "kernel": "grouped",
        "dispatcher_grants_per_sec": disp_per_sec,
        "dispatcher_pipelined_grants_per_sec": disp_pipe_per_sec,
        # Read the two numbers above through this label: "host" means
        # the pipeline has no RTT to hide and sync SHOULD win; only
        # under "remote_tunnel" (or a future multi-host "colocated"
        # with real transport) is pipelined-vs-sync a fair fight.
        "dispatcher_rtt_regime": rtt_regime,
        "heartbeats_per_sec": beats_per_sec,
        "bloom_fingerprint_mkeys_per_sec": bloom_fp,
        "dataplane_mb_per_sec": dataplane_mb,
        # (v5 documented this field but never emitted it — fixed in v6.)
        "jit_compiles_per_sec": jit_cps,
        "aot_fanout_compiles_per_sec": aot_cps,
        "autotune_sweep_dedup_ratio": autotune_dedup,
        "sharded_assignments_per_sec": sharded_aps,
        "device_resident_assignments_per_sec": resident.get(
            "assignments_per_sec"),
        "device_resident": resident,
        "policy_stage_p99_us": (resident_stage or {}).get(
            "policy_stage_p99_us"),
        "resident_policy_stage": resident_stage,
        "concurrent_connections": storm_conns,
        "grant_call_p99_ms": aio_grant_p99,
        "accept_loops_scaling": accept_scaling,
        "servant_parked_waiters": servant_parked,
        "overload_reject_p99_ms": hostile.get("overload_reject_p99_ms"),
        "survival_compile_success_rate": hostile.get(
            "survival_compile_success_rate"),
        "failover_time_ms": hostile.get("failover_time_ms"),
        "cell_kill_success_rate": hostile.get("cell_kill_success_rate"),
        "l3_read_through_hit_rate": coldregion.get(
            "l3_read_through_hit_rate"),
        "prefetch_time_to_warm_s": coldregion.get(
            "prefetch_time_to_warm_s"),
        "placement_warm_hit_rate": spill_affinity.get(
            "placement_warm_hit_rate"),
        "placement_score_p99_us": spill_affinity.get(
            "placement_score_p99_us"),
        "victim_tenant_slo_share": tenancy.get("victim_tenant_slo_share"),
        "cross_tenant_isolation_ok": tenancy.get(
            "cross_tenant_isolation_ok"),
        "pallas_ab": None,
        "pallas_grouped_ab": None,
        "device": str(jax.devices()[0]),
        # A CPU number must never masquerade as a TPU number.
        "cpu_fallback": not on_tpu,
    }
    # Print the complete headline result BEFORE the Pallas sections:
    # Mosaic lowering on real hardware is the riskiest step of the run,
    # and if it wedges the child, the orchestrator salvages the last
    # fully-formed JSON line from partial stdout — the TPU headline
    # number must not die with a Pallas experiment.
    print(json.dumps(result), flush=True)

    # Pallas A/Bs on EVERY platform (v10): native Mosaic compile on
    # real TPU hardware; the Pallas interpreter on CPU — parity is
    # checked either way, so `pallas_ab`/`pallas_grouped_ab` are never
    # null and a CPU-only harness still proves the kernel bodies agree
    # with the XLA kernels bit-for-bit.  pallas_grouped is the flagship
    # single-launch variant of the headline kernel — on TPU its number
    # is directly comparable; in interpret mode the number measures the
    # interpreter and is labeled via `mode`.
    if not os.environ.get("BENCH_SKIP_PALLAS"):
        ab_batches = 150 if on_tpu else 20
        try:
            result["pallas_ab"] = _pallas_ab(
                static, S, T, E_WORDS, rng, batches=ab_batches,
                interpret=not on_tpu)
        except Exception as e:  # Mosaic lowering is unproven on HW
            result["pallas_ab"] = {
                "error": f"{type(e).__name__}: {e}"[:300]}
        # Re-print after EACH section: if the next one hangs (a wedge,
        # not an exception), the completed A/B must already be on
        # stdout for the orchestrator's salvage.
        print(json.dumps(result), flush=True)
        try:
            result["pallas_grouped_ab"] = _pallas_grouped_ab(
                static, S, T, E_WORDS, G, G_PAD, rng,
                batches=ab_batches, interpret=not on_tpu)
        except Exception as e:
            result["pallas_grouped_ab"] = {
                "error": f"{type(e).__name__}: {e}"[:300]}
        print(json.dumps(result), flush=True)


def _bloom_fingerprint_metrics(n: int = 1_000_000) -> dict:
    """Mkeys/s of host cache-key fingerprinting (the Bloom control
    plane's hashing budget, BASELINE configs[3] prep): the r02 per-key
    C-call loop vs the vectorized pack+digest that replaced it.  The
    loop baseline runs on an n/8 subsample (it is the slow side and
    its cost is linear); see yadcc_tpu/tools/bloom_bench.py for the
    full three-way sweep with probe timings."""
    from yadcc_tpu.common import bloom
    from yadcc_tpu.common.xxh64_np import pack_key_matrix, xxh64_grouped

    keys = [f"ytpu-cxx2-entry-{i:07d}" for i in range(n)]
    m = max(1, n // 8)
    t0 = time.perf_counter()
    bloom.key_fingerprints_loop(keys[:m], 17)
    t_loop = (time.perf_counter() - t0) * (n / m)
    t0 = time.perf_counter()
    mat, lens = pack_key_matrix(keys)
    t_pack = time.perf_counter() - t0
    t0 = time.perf_counter()
    bloom._split_digests(xxh64_grouped(mat, lens, 17))
    t_vec = time.perf_counter() - t0
    return {
        "batch_keys": n,
        "host_loop": round(n / t_loop / 1e6, 2),
        "host_vectorized_digest": round(n / t_vec / 1e6, 2),
        "host_vectorized_end_to_end": round(n / (t_pack + t_vec) / 1e6,
                                            2),
        "speedup_digest": round(t_loop / t_vec, 1),
        "speedup_end_to_end": round(t_loop / (t_pack + t_vec), 1),
    }


def _heartbeat_throughput(n_servants: int = 5000, n: int = 10000) -> float:
    """Heartbeat-handler calls/sec with a full registry — the other
    half of scheduler load (a 5k fleet beats at 5k/s; this shows the
    headroom)."""
    from yadcc_tpu import api
    from yadcc_tpu.rpc.transport import RpcContext
    from yadcc_tpu.scheduler.policy import GreedyCpuPolicy
    from yadcc_tpu.scheduler.service import SchedulerService
    from yadcc_tpu.scheduler.task_dispatcher import TaskDispatcher
    from yadcc_tpu.utils.clock import VirtualClock

    d = TaskDispatcher(GreedyCpuPolicy(), max_servants=8192, max_envs=256,
                       clock=VirtualClock(0), batch_window_s=0.0,
                       start_dispatch_thread=False)
    svc = SchedulerService(d)

    def beat(i):
        req = api.scheduler.HeartbeatRequest(
            token="", version=1,
            location=f"10.{i >> 16}.{(i >> 8) & 255}.{i & 255}:8335",
            capacity=16, num_processors=32,
            memory_available_in_bytes=64 << 30,
            next_heartbeat_in_ms=10000)
        req.env_descs.add(compiler_digest=f"env{i % 64}")
        svc.Heartbeat(req, b"", RpcContext(
            peer=f"10.{i >> 16}.{(i >> 8) & 255}.{i & 255}:999"))

    for i in range(n_servants):
        beat(i)
    t0 = time.perf_counter()
    for k in range(n):
        beat(k % n_servants)
    dt = time.perf_counter() - t0
    d.stop()
    return round(n / dt, 1)


def _pallas_ab(static, S, T, E_WORDS, rng, batches: int = 150,
               interpret: bool = False) -> dict:
    """Compile the Pallas kernel at the production shape, check parity
    against the exact scan kernel, and time it.  `interpret=False` is
    the TPU path (Mosaic native compile — the validation a CPU run
    can't provide); `interpret=True` runs the same kernel body through
    the Pallas interpreter on CPU, so every harness emits a non-null
    parity verdict (v10) — its assignments/s measures the interpreter,
    not the kernel, and is labeled as such via `mode`."""
    import jax
    import jax.numpy as jnp

    from yadcc_tpu.ops import assignment as asn
    from yadcc_tpu.ops.pallas_assign import pallas_assign_batch

    running = jnp.zeros(S, jnp.int32)
    pool = asn.PoolArrays(running=running, **static)
    envs = list(rng.integers(0, E_WORDS * 32, T))
    batch = asn.make_batch(envs, [1] * T, [-1] * T, pad_to=T)

    p_picks, p_running = pallas_assign_batch(
        pool, batch, interpret=interpret)                   # compiles
    s_picks, s_running = asn.assign_batch(pool, batch)
    parity = bool(
        np.array_equal(np.asarray(p_picks), np.asarray(s_picks))
        and np.array_equal(np.asarray(p_running), np.asarray(s_running)))

    # Same steady-state shape and pipelined harness as the headline
    # loop, so the numbers are directly comparable.  Grants are counted
    # as picks >= 0, mapped through the same drain path by summing a
    # device-side 0/1 vector.
    trim = _occupancy_trimmer(static)

    @jax.jit
    def step(b, running):
        picks, running = pallas_assign_batch(
            asn.PoolArrays(running=running, **static), b,
            interpret=interpret)
        return (picks >= 0).astype(jnp.int32), trim(running)

    running, per_sec, _, _, _ = _pipelined_run(
        step, lambda _i: batch, running, trim=None,
        batches=batches, warmup=3,
        window=int(os.environ.get("BENCH_WINDOW",
                                  1 if interpret else 64)))
    return {
        "mode": "interpret" if interpret else "native",
        "native_compile_ok": not interpret,
        "parity_with_scan_kernel": parity,
        "assignments_per_sec": round(per_sec, 1),
    }


def _pallas_grouped_ab(static, S, T, E_WORDS, G, G_PAD, rng,
                       batches: int = 150,
                       interpret: bool = False) -> dict:
    """The headline grouped workload through the single-launch Pallas
    kernel: parity vs the XLA grouped kernel, then timed at the same
    steady-state occupancy.  `interpret=True` is the CPU path (v10):
    same kernel body through the Pallas interpreter, parity checked
    against the XLA grouped kernel AND the fused resident step — the
    throughput number then measures the interpreter, labeled `mode`."""
    import jax
    import jax.numpy as jnp

    from yadcc_tpu.ops import assignment as asn
    from yadcc_tpu.ops import assignment_grouped as asg
    from yadcc_tpu.ops.pallas_grouped import (
        pallas_assign_grouped, pallas_assign_grouped_picks_packed,
        pallas_resident_grouped_step)

    running = jnp.zeros(S, jnp.int32)
    pool = asn.PoolArrays(running=running, **static)
    batch = asg.make_grouped_batch(_make_groups(rng, T, G, E_WORDS),
                                   pad_to=G_PAD)
    p_counts, p_running = pallas_assign_grouped(
        pool, batch, interpret=interpret)                   # compiles
    x_counts, x_running = asg.assign_grouped(pool, batch)
    parity = bool(
        np.array_equal(np.asarray(p_counts), np.asarray(x_counts))
        and np.array_equal(np.asarray(p_running), np.asarray(x_running)))

    # The device-resident twin (ops resident_grouped_step vs its Pallas
    # variant): one empty-delta fused step from the same pool, both
    # sides must agree bit-for-bit on picks and the advanced pool.
    t_pad = asg.task_pad(T)
    packed0 = asg.make_grouped_packed(_make_groups(rng, T, G, E_WORDS),
                                      pad_to=G_PAD)
    host = {k: np.asarray(v) for k, v in static.items()}
    delta0 = asg.make_pool_delta(np.zeros(0, np.int64), host,
                                 pad_to=asg.delta_pad(0), pool_size=S)
    zadj = jnp.zeros(S, jnp.int32)
    zmask = jnp.zeros(S, bool)
    zval = jnp.zeros(S, jnp.int32)

    def fresh_pool():
        return asn.PoolArrays(running=jnp.zeros(S, jnp.int32),
                              **{k: jnp.asarray(v)
                                 for k, v in host.items()})

    r_picks, r_pool = asg.resident_grouped_step(
        fresh_pool(), delta0, packed0, zadj, zmask, zval, t_pad)
    q_picks, q_pool = pallas_resident_grouped_step(
        fresh_pool(), delta0, packed0, zadj, zmask, zval, t_pad,
        interpret=interpret)
    resident_parity = bool(
        np.array_equal(np.asarray(r_picks), np.asarray(q_picks))
        and np.array_equal(np.asarray(r_pool.running),
                           np.asarray(q_pool.running)))

    trim = _occupancy_trimmer(static)

    @jax.jit
    def step(packed, running):
        picks, running = pallas_assign_grouped_picks_packed(
            asn.PoolArrays(running=running, **static), packed, t_pad,
            interpret=interpret)
        return picks, trim(running)

    def mkbatch(_i):
        return asg.make_grouped_packed(_make_groups(rng, T, G, E_WORDS),
                                       pad_to=G_PAD)

    running, per_sec, _, _, _ = _pipelined_run(
        step, mkbatch, running, trim=None,
        batches=batches, warmup=3,
        window=int(os.environ.get("BENCH_WINDOW",
                                  1 if interpret else 64)),
        count_fn=lambda arr: int((arr >= 0).sum()))
    return {
        "mode": "interpret" if interpret else "native",
        "native_compile_ok": not interpret,
        "parity_with_xla_grouped": parity,
        "resident_step_parity": resident_parity,
        "assignments_per_sec": round(per_sec, 1),
    }


def _device_resident_throughput(S: int, E_WORDS: int,
                                passes: int = 3) -> dict:
    """The device-resident dispatch microbench (v10, the tentpole
    number): the pool NEVER leaves the device — statics scatter in as
    tiny heartbeat deltas (one 4-slot delta every 16th step, cached
    empty delta otherwise), running corrections ride the fused fold,
    and each step is ONE launch with buffer donation.  Per-launch depth
    is the production task cap (ops task_pad ladder top, 2048): the
    whole point of residency is that the policy stage stops being the
    cycle bottleneck, so the dispatcher drains its full backlog cap per
    launch instead of pacing uploads.

    Platform split mirrors policy._decide_expand: picks expansion on
    device where transfers are the cost (TPU), the counts twin where
    the dense expansion compare is pure overhead (CPU).  Steady state:
    every step's fold resets running to the 55%-occupancy baseline —
    the FreeTask stream expressed through the reset protocol, off the
    host path entirely."""
    import collections

    import jax
    import jax.numpy as jnp

    from yadcc_tpu.ops import assignment as asn
    from yadcc_tpu.ops import assignment_grouped as asg

    on_tpu = jax.devices()[0].platform == "tpu"
    T = int(os.environ.get("BENCH_RES_BATCH", 2048))
    G = int(os.environ.get("BENCH_GROUPS", 4))
    BATCHES = int(os.environ.get("BENCH_RES_BATCHES", 200))
    G_PAD = asg.group_pad(G)
    t_pad = asg.task_pad(T)
    window = int(os.environ.get("BENCH_WINDOW", 64 if on_tpu else 8))
    CHURN = 16                       # heartbeat delta every 16th step

    # This section owns its pool buffers outright: the fused step
    # donates the pool, so seeding from the shared `static` dict would
    # invalidate the headline sections' arrays.
    rng = np.random.default_rng(43)
    host = dict(
        alive=rng.random(S) < 0.95,
        capacity=rng.integers(8, 64, S).astype(np.int32),
        dedicated=rng.random(S) < 0.3,
        version=np.ones(S, np.int32),
        env_bitmap=rng.integers(0, 2 ** 32, (S, E_WORDS),
                                dtype=np.uint64).astype(np.uint32),
    )
    base_running = (host["capacity"] * host["alive"]
                    * 0.55).astype(np.int32)
    adj = jnp.zeros(S, jnp.int32)
    rmask = jnp.ones(S, bool)
    rval = jnp.asarray(base_running)
    d_pad = asg.delta_pad(4)
    empty = asg.make_pool_delta(np.zeros(0, np.int64), host,
                                pad_to=d_pad, pool_size=S)

    # Workload pre-generated, as in the headline loop: only the
    # dispatcher's own work (delta/descriptor packing, the launch, the
    # drain) belongs inside the measured cycle.
    n_wl = BATCHES + 8
    wl = []
    for i in range(n_wl):
        envs = rng.integers(0, E_WORDS * 32, G)
        sizes = np.full(G, T // G, np.int32)
        sizes[: T % G] += 1
        wl.append(([(int(e), 1, -1, int(m))
                    for e, m in zip(envs, sizes)],
                   rng.integers(0, S, 4).astype(np.int64)))

    def mk(i):
        descr, didx = wl[i % n_wl]
        packed = asg.make_grouped_packed(descr, pad_to=G_PAD)
        if i % CHURN == 0:
            return packed, asg.make_pool_delta(
                didx, host, pad_to=d_pad, pool_size=S)
        return packed, empty

    if on_tpu:
        def step(pool, delta, packed):
            return asg.resident_grouped_step(
                pool, delta, packed, adj, rmask, rval, t_pad)

        count = lambda arr: int((arr >= 0).sum())
    else:
        def step(pool, delta, packed):
            return asg.resident_grouped_step_counts(
                pool, delta, packed, adj, rmask, rval)

        count = lambda arr: int(arr.sum())

    from yadcc_tpu.utils import gctune

    per_pass = []
    with gctune.guard():
        for _ in range(max(1, passes)):
            pool = asn.PoolArrays(
                running=jnp.zeros(S, jnp.int32),
                **{k: jnp.asarray(v) for k, v in host.items()})
            for i in range(3):
                packed, delta = mk(i)
                out, pool = step(pool, delta, packed)
            inflight = collections.deque()
            granted = 0
            t0 = time.perf_counter()
            for i in range(BATCHES):
                packed, delta = mk(i)
                out, pool = step(pool, delta, packed)
                out.copy_to_host_async()
                inflight.append(out)
                if len(inflight) >= window:
                    granted += count(np.asarray(inflight.popleft()))
            while inflight:
                granted += count(np.asarray(inflight.popleft()))
            per_pass.append(granted / (time.perf_counter() - t0))
    return {
        "assignments_per_sec": round(float(np.median(per_pass)), 1),
        "passes": [round(x, 1) for x in per_pass],
        "per_launch_tasks": T,
        "mode": "picks" if on_tpu else "counts",
        "churn_every": CHURN,
    }


def _resident_policy_stage_metrics(n_servants: int = 5000,
                                   duration_s: float = 3.0) -> dict:
    """The FULL dispatcher in pipelined mode with the device-resident
    policy (scheduler/policy.py JaxResidentGroupedPolicy): the same rig
    as _dispatcher_pipelined_throughput, but what's under test is the
    POLICY STAGE — with residency, stream_launch is delta assembly plus
    an async dispatch, so its host time (StageTimer "policy") should be
    microseconds regardless of pool size.  Returns the policy-stage p99
    in us plus the rig's grants/s as context."""
    import threading

    from yadcc_tpu.scheduler.policy import make_policy
    from yadcc_tpu.scheduler.task_dispatcher import (ServantInfo,
                                                     TaskDispatcher)

    policy = make_policy("jax_resident_grouped", 8192)
    policy.stream_warmup(8192)
    d = TaskDispatcher(policy, max_servants=8192, max_envs=256,
                       batch_window_s=0.0, pipeline_depth=16,
                       start_dispatch_thread=True)
    rng = np.random.default_rng(7)
    for i in range(n_servants):
        d.keep_servant_alive(ServantInfo(
            location=f"10.{i >> 16}.{(i >> 8) & 255}.{i & 255}:8335",
            version=1, capacity=int(rng.integers(8, 64)),
            num_processors=64, memory_available=64 << 30,
            dedicated=bool(rng.random() < 0.3),
            env_digests=(f"env{i % 8}",)), 3600.0)

    stop = threading.Event()

    def waiter(j):
        while not stop.is_set():
            got = d.wait_for_starting_new_task(
                f"env{j % 4}", immediate=16, timeout_s=2.0)
            if got:
                d.free_task([gid for gid, _ in got])

    threads = [threading.Thread(target=waiter, args=(j,), daemon=True)
               for j in range(128)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    base = d._stats["granted"]
    time.sleep(duration_s)
    granted = d._stats["granted"] - base
    stop.set()
    for t in threads:
        t.join(timeout=3)
    stages = d.stage_timer.percentiles()
    stream = (policy.stream_stats()
              if hasattr(policy, "stream_stats") else {})
    d.stop()
    pol = stages.get("policy") or {}
    p99_ms = pol.get("p99_ms")
    return {
        "policy_stage_p99_us": (round(p99_ms * 1000.0, 1)
                                if p99_ms is not None else None),
        "policy_stage_samples": pol.get("count"),
        "grants_per_sec": round(granted / duration_s, 1),
        "stream": stream,
    }


def _dispatcher_pipelined_throughput(n_servants: int = 5000,
                                     duration_s: float = 4.0) -> float:
    """Grants/sec through the FULL dispatcher in pipelined mode: the
    real dispatch thread, device-resident running chain, waiter threads
    blocking on grants, frees riding the correction stream.  This is
    the path a TPU-attached scheduler actually serves on — the sync
    number (dispatcher_grants_per_sec) pays a device round-trip per
    cycle, which on a remote-attached accelerator is the bottleneck."""
    import threading

    from yadcc_tpu.scheduler.policy import JaxGroupedPolicy
    from yadcc_tpu.scheduler.task_dispatcher import (ServantInfo,
                                                     TaskDispatcher)

    policy = JaxGroupedPolicy()
    # Production boot order (scheduler entry): compile the stream
    # kernel's shape ladder BEFORE serving, or the first live launches
    # stall on jit compiles.
    policy.stream_warmup(8192)
    d = TaskDispatcher(policy, max_servants=8192, max_envs=256,
                       batch_window_s=0.0, pipeline_depth=16,
                       start_dispatch_thread=True)
    rng = np.random.default_rng(7)
    for i in range(n_servants):
        d.keep_servant_alive(ServantInfo(
            location=f"10.{i >> 16}.{(i >> 8) & 255}.{i & 255}:8335",
            version=1, capacity=int(rng.integers(8, 64)),
            num_processors=64, memory_available=64 << 30,
            dedicated=bool(rng.random() < 0.3),
            env_digests=(f"env{i % 8}",)), 3600.0)

    stop = threading.Event()

    # Concurrency models a real fleet: hundreds of delegates blocked in
    # WaitForStartingTask at once.  Grant latency per delegate is one
    # device round-trip, so in-flight demand (waiters x immediate) must
    # cover the RTT for the pipeline to stay full — exactly like the
    # production scenario this mode exists for.
    def waiter(j):
        while not stop.is_set():
            got = d.wait_for_starting_new_task(
                f"env{j % 4}", immediate=16, timeout_s=2.0)
            if got:
                d.free_task([gid for gid, _ in got])

    threads = [threading.Thread(target=waiter, args=(j,), daemon=True)
               for j in range(128)]
    for t in threads:
        t.start()
    time.sleep(0.5)                       # spin-up + first compiles
    base = d._stats["granted"]
    time.sleep(duration_s)
    granted = d._stats["granted"] - base
    stop.set()
    for t in threads:
        t.join(timeout=3)
    d.stop()
    return round(granted / duration_s, 1)


def _dispatcher_cycle_throughput(n_servants: int = 5000,
                                 backlog: int = 512,
                                 cycles: int = 30) -> float:
    from yadcc_tpu.scheduler.policy import JaxGroupedPolicy
    from yadcc_tpu.scheduler.task_dispatcher import (ServantInfo,
                                                     TaskDispatcher)
    from yadcc_tpu.utils.clock import VirtualClock

    clock = VirtualClock(0)
    d = TaskDispatcher(JaxGroupedPolicy(), max_servants=8192, max_envs=256,
                       clock=clock, batch_window_s=0.0,
                       start_dispatch_thread=False)
    rng = np.random.default_rng(7)
    for i in range(n_servants):
        d.keep_servant_alive(ServantInfo(
            location=f"10.{i >> 16}.{(i >> 8) & 255}.{i & 255}:8335",
            version=1, capacity=int(rng.integers(8, 64)),
            num_processors=64, memory_available=64 << 30,
            dedicated=bool(rng.random() < 0.3),
            env_digests=(f"env{i % 8}",)), 3600.0)

    import threading

    granted = 0
    t0 = None
    for c in range(cycles + 1):
        # A fresh 512-request backlog each cycle, a few envs (one build
        # floods one env), waited on by threads like real RPC handlers.
        threads = [
            threading.Thread(
                target=d.wait_for_starting_new_task,
                args=(f"env{j % 4}",),
                kwargs=dict(immediate=backlog // 8, timeout_s=5.0),
                daemon=True)
            for j in range(8)
        ]
        for t in threads:
            t.start()
        # Let the waiters park before the single explicit cycle (cheap
        # probe — inspect() builds the full servant table).
        deadline = time.time() + 2
        while time.time() < deadline and len(d._pending) < 8:
            time.sleep(0.001)
        if c == 1:
            t0 = time.perf_counter()
        n = d.run_dispatch_cycle_for_testing()
        if c >= 1:
            granted += n
        for t in threads:
            t.join(timeout=5)
        # Retire everything so the pool never saturates.
        d.free_task([g.grant_id for g in d.get_running_tasks()])
    elapsed = time.perf_counter() - t0
    d.stop()
    return round(granted / elapsed, 1)


def _orchestrate() -> None:
    """Run the measurement in a child process with a watchdog: a wedged
    accelerator tunnel must degrade to a CPU number, not a hang."""
    import subprocess
    import sys

    env = dict(os.environ, BENCH_CHILD="1")
    for attempt_env in (env, dict(env, BENCH_FORCE_CPU="1")):
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=attempt_env, capture_output=True, text=True,
                timeout=int(os.environ.get("BENCH_TIMEOUT", 600)),
            )
        except subprocess.TimeoutExpired as e:
            # The child prints a complete headline JSON line before the
            # risky Pallas sections; if the wedge hit later, that line
            # is still the real measurement — salvage it.
            partial = e.stdout or b""
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            lines = [l for l in partial.splitlines() if l.startswith("{")]
            if lines:
                print(lines[-1])
                return
            continue
        lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
        if out.returncode == 0 and lines:
            print(lines[-1])
            return
    print(json.dumps({
        "metric": "scheduler_assignments_per_sec_5k_workers",
        "value": 0, "unit": "assignments/s", "vs_baseline": 0.0,
        "error": "benchmark could not run on any backend",
    }))


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD"):
        main()
    else:
        _orchestrate()
